"""Crash-safe feature store (ncnet_tpu/store/): the chaos ladder.

The store's one invariant — a query NEVER fails because of the store and
NEVER uses unverified bytes — is executed here under every injected fault
the design claims to survive: SIGKILL between payload write and commit
rename (a rerun sees no torn entry and rebuilds), a post-commit bit flip
(detected, quarantined, recomputed bitwise-identical to the cold path),
ENOSPC on read/write (fail-open recompute with the DEGRADED → recovered
timeline in the event log), fingerprint skew (miss + superseded-generation
GC), and the LRU budget with its journal.  THE acceptance chain: a
warm-store InLoc query performs exactly ONE backbone extraction
(spy-counted) and writes match tables bitwise-identical to the uncached
path.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest
from scipy.io import loadmat

import jax

from ncnet_tpu.config import EvalInLocConfig, ModelConfig
from ncnet_tpu.data.synthetic import write_inloc_like
from ncnet_tpu.evaluation.inloc import make_pair_matcher, run_inloc_eval
from ncnet_tpu.models.ncnet import init_ncnet
from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.observability.events import EventLog, replay_events
from ncnet_tpu.store import (
    FeatureStore,
    backbone_fingerprint,
    content_digest,
    weights_digest,
)
from ncnet_tpu.utils import faults

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

TINY = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                   ncons_channels=(1,), half_precision=True,
                   relocalization_k_size=2)


@pytest.fixture
def arr(rng):
    return rng.standard_normal((3, 4, 8)).astype(np.float32)


def _store(tmp_path, fp="aaaa0000-s128-k2-bf16", **kw):
    return FeatureStore(str(tmp_path / "fstore"), fp, **kw)


# ---------------------------------------------------------------------------
# keys / fingerprints
# ---------------------------------------------------------------------------


def test_content_digest_and_fingerprint_identity():
    """The digest covers bytes AND shape/dtype; the fingerprint covers the
    TRUNK weights + extraction settings but deliberately NOT the NC-filter
    params (retraining only the filter must not invalidate the database)."""
    a = np.arange(24, dtype=np.uint8).reshape(2, 3, 4)
    assert content_digest(a) == content_digest(a.copy())
    assert content_digest(a) != content_digest(a.reshape(4, 3, 2))
    assert content_digest(a) != content_digest(a.astype(np.int16))
    b = a.copy()
    b[0, 0, 0] ^= 1
    assert content_digest(a) != content_digest(b)

    params = init_ncnet(TINY, jax.random.key(0))
    params2 = init_ncnet(TINY, jax.random.key(1))
    assert weights_digest(params) != weights_digest(params2)
    # NC params excluded: a filter-only change keeps the generation
    import copy

    p3 = copy.deepcopy(params)
    p3["nc"][0]["b"] = np.asarray(p3["nc"][0]["b"]) + 1.0
    assert weights_digest(params) == weights_digest(p3)
    fp = backbone_fingerprint(params, image_size=128, k_size=2, dtype="bf16")
    assert fp != backbone_fingerprint(params, image_size=256, k_size=2,
                                      dtype="bf16")
    assert fp != backbone_fingerprint(params, image_size=128, k_size=1,
                                      dtype="bf16")
    assert fp != backbone_fingerprint(params2, image_size=128, k_size=2,
                                      dtype="bf16")


# ---------------------------------------------------------------------------
# verified persistence across restarts
# ---------------------------------------------------------------------------


def test_roundtrip_persists_across_reopen(tmp_path, arr):
    s = _store(tmp_path)
    d = content_digest(arr)
    got, status = s.resolve(d, lambda: arr)
    assert status == "miss"
    np.testing.assert_array_equal(got, arr)
    got2, status2 = s.resolve(
        d, lambda: (_ for _ in ()).throw(AssertionError("must not compute")))
    assert status2 == "hit"
    np.testing.assert_array_equal(got2, arr)
    s.close()

    # a fresh process (new store object) reads the committed entry back
    s2 = _store(tmp_path)
    assert s2.entries == 1
    got3 = s2.get(d)
    np.testing.assert_array_equal(got3, arr)
    assert s2.counters["hits"] == 1
    s2.close()


def test_fingerprint_skew_is_miss_and_gc_superseded(tmp_path, arr):
    """New weights → a different generation directory: reads miss, and GC
    removes the dead generation while keeping same-weights siblings (the
    serving engine's other image_size consumer)."""
    d = content_digest(arr)
    old = _store(tmp_path, fp="deadbeef00000000-s128-k2-bf16")
    old.put(d, arr)
    old.close()
    sibling = _store(tmp_path, fp="aaaa0000-s999-k1-f32")
    sibling.put(d, arr)
    sibling.close()

    s = _store(tmp_path, fp="aaaa0000-s128-k2-bf16")
    assert s.get(d) is None  # the old generation's entry is invisible
    assert s.counters["misses"] == 1
    assert s.gc_superseded() == 1  # deadbeef generation removed
    root = str(tmp_path / "fstore")
    assert sorted(n for n in os.listdir(root) if not n.startswith("quar")) \
        == ["aaaa0000-s128-k2-bf16", "aaaa0000-s999-k1-f32"]
    s.close()


# ---------------------------------------------------------------------------
# chaos ladder: SIGKILL mid-commit / bit flip / ENOSPC
# ---------------------------------------------------------------------------


def test_sigkill_mid_commit_leaves_no_visible_entry(tmp_path):
    """SIGKILL between the payload write and the commit rename: the store
    directory holds a .tmp carcass and NO visible entry; a rerun opens
    clean and rebuilds the entry from scratch."""
    root = str(tmp_path / "fstore")
    worker = tmp_path / "worker.py"
    worker.write_text(f"""
import sys
sys.path.insert(0, {_REPO!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from ncnet_tpu.store import FeatureStore, content_digest

s = FeatureStore({root!r}, "aaaa0000-s128-k2-bf16")
a = np.arange(240, dtype=np.float32).reshape(3, 80)
s.put(content_digest(a), a)
raise SystemExit("unreachable: the commit kill hook must have fired")
""")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["NCNET_TPU_FAULTS"] = json.dumps({"kill_at_store_commit": 1})
    proc = subprocess.run(
        [sys.executable, str(worker)], env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=300)
    assert proc.returncode == -9, \
        f"expected SIGKILL, got:\n{proc.stdout[-3000:]}"

    gen = os.path.join(root, "aaaa0000-s128-k2-bf16")
    names = os.listdir(gen)
    assert not [n for n in names if n.endswith(".feat")], names
    assert [n for n in names if ".feat.tmp." in n], names  # the carcass

    # the rerun sees an empty generation and rebuilds
    s = FeatureStore(root, "aaaa0000-s128-k2-bf16")
    assert s.entries == 0
    a = np.arange(240, dtype=np.float32).reshape(3, 80)
    got, status = s.resolve(content_digest(a), lambda: a)
    assert status == "miss"
    got2 = s.get(content_digest(a))
    np.testing.assert_array_equal(got2, a)
    s.close()


def test_bitflip_detected_quarantined_recomputed_bitwise(tmp_path, arr):
    """A post-commit payload bit flip must be caught by the checksum on
    the next read: the entry is quarantined (bytes preserved, never
    served), the value recomputed bitwise-identical to the cold path, and
    the rewrite serves verified hits again."""
    events_path = str(tmp_path / "events.jsonl")
    sink = EventLog(events_path)
    prev = obs_events.set_global_sink(sink)
    try:
        s = _store(tmp_path)
        d = content_digest(arr)
        with faults.injected(faults.FaultPlan(store_bitflip_paths=(d,))):
            s.put(d, arr)  # committed, then corrupted post-commit
        got, status = s.resolve(d, lambda: arr)
        assert status == "recompute"
        np.testing.assert_array_equal(got, arr)  # bitwise = the cold path
        assert s.counters["corrupt"] == 1
        assert s.state == "OK"  # corruption is not degradation
        qdir = os.path.join(str(tmp_path / "fstore"), "quarantine")
        assert len(os.listdir(qdir)) == 1  # the evidence survives
        # the rewrite is a verified hit now
        got2, status2 = s.resolve(
            d, lambda: (_ for _ in ()).throw(AssertionError("no compute")))
        assert status2 == "hit"
        np.testing.assert_array_equal(got2, arr)
        s.close()
    finally:
        obs_events.set_global_sink(prev)
        sink.close()
    _, events = replay_events(events_path)
    corrupt = [e for e in events if e["event"] == "store_corrupt"]
    assert len(corrupt) == 1 and corrupt[0]["reason"] == "checksum mismatch"


def test_enospc_fails_open_with_degraded_recovered_timeline(tmp_path, arr):
    """Injected ENOSPC on write then read: every resolve still answers
    (recompute), the store marks itself DEGRADED, and the first later
    success transitions back to OK — the DEGRADED → recovered timeline
    replayable from the event log."""
    events_path = str(tmp_path / "events.jsonl")
    sink = EventLog(events_path)
    prev = obs_events.set_global_sink(sink)
    try:
        s = _store(tmp_path)
        d = content_digest(arr)
        with faults.injected(faults.FaultPlan(store_io_error_ops=("write",))):
            got, status = s.resolve(d, lambda: arr)
        assert status == "miss"
        np.testing.assert_array_equal(got, arr)  # the query never failed
        assert s.state == "DEGRADED"
        # disk recovers: the next resolve commits and the store recovers
        got2, status2 = s.resolve(d, lambda: arr)
        assert status2 == "miss"  # the degraded write never landed
        assert s.state == "OK"
        # now injected READ failure over an existing entry: fail-open
        # recompute (degrading mid-resolve), and the successful rewrite
        # recovers the store within the same resolve — both transitions
        # land in the timeline
        with faults.injected(faults.FaultPlan(store_io_error_ops=("read",))):
            got3, status3 = s.resolve(d, lambda: arr)
        assert status3 == "recompute"
        np.testing.assert_array_equal(got3, arr)
        assert s.state == "OK"
        got4, status4 = s.resolve(
            d, lambda: (_ for _ in ()).throw(AssertionError("no compute")))
        assert status4 == "hit" and s.state == "OK"
        s.close()
    finally:
        obs_events.set_global_sink(prev)
        sink.close()
    _, events = replay_events(events_path)
    timeline = [e["state"] for e in events if e["event"] == "store_health"]
    assert timeline == ["DEGRADED", "OK", "DEGRADED", "OK"]


def test_journal_failure_keeps_store_degraded(tmp_path, arr):
    """A journal/evict failure INSIDE an otherwise-successful operation
    must leave the store DEGRADED — the operation's own success path may
    not erase a failure that landed while it ran (recovery requires a
    later operation with NO failures)."""
    s = _store(tmp_path)
    d = content_digest(arr)
    s.resolve(d, lambda: arr)
    with faults.injected(faults.FaultPlan(store_io_error_ops=("journal",))):
        got = s.get(d)  # the read succeeds; its touch journaling fails
        np.testing.assert_array_equal(got, arr)
        assert s.state == "DEGRADED"
        got = s.get(d)  # still failing: stays DEGRADED, still answers
        np.testing.assert_array_equal(got, arr)
        assert s.state == "DEGRADED"
    got = s.get(d)  # journal healthy again: THIS op claims recovery
    np.testing.assert_array_equal(got, arr)
    assert s.state == "OK"
    s.close()


def test_journal_compacts_in_process(tmp_path, arr):
    """A warm long-lived process must not grow the journal one touch
    record per hit forever: once appends dwarf the live entry set the
    journal compacts in place to one put-record per entry."""
    s = _store(tmp_path)
    d = content_digest(arr)
    s.resolve(d, lambda: arr)
    for _ in range(200):
        s.get(d)
    journal = os.path.join(str(tmp_path / "fstore"),
                           "aaaa0000-s128-k2-bf16", "journal.jsonl")
    with open(journal) as f:
        lines = f.readlines()
    assert len(lines) <= 70  # compacted well below the 200+ appends
    # and the compacted journal still round-trips the LRU index
    s.close()
    s2 = _store(tmp_path)
    assert s2.entries == 1 and s2.get(d) is not None
    s2.close()


# ---------------------------------------------------------------------------
# LRU budget + journal
# ---------------------------------------------------------------------------


def test_lru_eviction_keeps_store_under_budget(tmp_path, rng):
    arrays = [rng.standard_normal((4, 64)).astype(np.float32)
              for _ in range(4)]
    one = 4 * 64 * 4 + 300  # payload + header slack
    s = _store(tmp_path, budget_bytes=2 * one)
    digests = [content_digest(a) for a in arrays]
    for d, a in zip(digests[:3], arrays[:3]):
        s.resolve(d, lambda a=a: a)
    assert s.entries == 2 and s.counters["evictions"] == 1
    assert s.bytes_used <= 2 * one
    assert s.get(digests[0]) is None  # the oldest was the victim
    # touching the older survivor protects it: the NEXT eviction takes the
    # untouched one
    assert s.get(digests[1]) is not None
    s.resolve(digests[0], lambda: arrays[0])  # re-add -> evicts digests[2]
    assert s.get(digests[2]) is None
    assert s.get(digests[1]) is not None

    # the journal records the history and a reopen rebuilds LRU order
    journal = os.path.join(str(tmp_path / "fstore"),
                           "aaaa0000-s128-k2-bf16", "journal.jsonl")
    ops = [json.loads(line)["op"] for line in open(journal)]
    assert ops.count("evict") == 2 and "put" in ops and "touch" in ops
    s.close()
    s2 = _store(tmp_path, budget_bytes=2 * one)
    assert s2.entries == 2
    # journal-replayed order: digests[1]'s LAST touch postdates
    # digests[0]'s re-put, so the reopened store evicts digests[0] first —
    # access order survived the restart
    s2.resolve(digests[3], lambda: arrays[3])
    assert s2.get(digests[0]) is None
    assert s2.contains(digests[1]) and s2.contains(digests[3])
    s2.close()


# ---------------------------------------------------------------------------
# THE acceptance chain: store-backed InLoc eval
# ---------------------------------------------------------------------------


def _inloc_fixture(tmp_path, n_queries=2, n_panos=2):
    root = str(tmp_path)
    shortlist = write_inloc_like(root, n_queries=n_queries, n_panos=n_panos,
                                 image_hw=(96, 128))
    params = init_ncnet(TINY, jax.random.key(1))
    kw = dict(inloc_shortlist=shortlist, k_size=2, image_size=128,
              n_queries=n_queries, n_panos=n_panos,
              pano_path=os.path.join(root, "pano"),
              query_path=os.path.join(root, "query", "iphone7"))
    return root, params, kw


def _matches(out_dir):
    return {n: loadmat(os.path.join(out_dir, n))["matches"]
            for n in os.listdir(out_dir) if n.endswith(".mat")}


def test_warm_store_eval_one_extraction_and_identical_tables(tmp_path):
    """Acceptance: a warm-store InLoc query performs exactly ONE backbone
    extraction (spy-counted through the matcher's trunk call site) and
    produces match tables bitwise-identical to the uncached path; the
    eval_summary event carries the store counters proving hits == pairs."""
    root, params, kw = _inloc_fixture(tmp_path)
    sd = os.path.join(root, "fstore")

    plain = run_inloc_eval(
        EvalInLocConfig(output_root=os.path.join(root, "m0"), **kw),
        model_config=TINY, params=params, progress=False)
    cold = run_inloc_eval(
        EvalInLocConfig(output_root=os.path.join(root, "m1"),
                        feature_store_dir=sd, **kw),
        model_config=TINY, params=params, progress=False)
    warm = run_inloc_eval(
        EvalInLocConfig(output_root=os.path.join(root, "m2"),
                        feature_store_dir=sd,
                        telemetry_dir=os.path.join(root, "t2"), **kw),
        model_config=TINY, params=params, progress=False)

    a, b, c = _matches(plain), _matches(cold), _matches(warm)
    assert sorted(a) == sorted(b) == sorted(c) == ["1.mat", "2.mat"]
    for name in a:
        np.testing.assert_array_equal(a[name], b[name])
        np.testing.assert_array_equal(a[name], c[name])

    _, events = replay_events(os.path.join(root, "t2", "events.jsonl"))
    summary = [e for e in events if e["event"] == "eval_summary"][-1]
    # ONE extraction per query (its own trunk), zero for the database side
    assert summary["feature_extractions"] == 2
    st = summary["store"]
    assert st["state"] == "OK"
    assert st["counters"]["hits"] == 4 and st["counters"]["misses"] == 0
    # the durable stats twin rides the same log
    stats = [e for e in events if e["event"] == "store_stats"]
    assert stats and stats[-1]["store"]["counters"]["hits"] == 4


def test_eval_survives_corruption_and_enospc_with_identical_tables(tmp_path):
    """The chaos bar on the REAL consumer: with every committed entry
    bit-flipped post-commit (run 1) and the disk failing reads AND writes
    (run 2), every query still completes and every match table stays
    bitwise-identical to the uncached path — corrupt entries quarantine +
    recompute, I/O failures fail open with the store DEGRADED in the
    summary."""
    root, params, kw = _inloc_fixture(tmp_path)
    sd = os.path.join(root, "fstore")
    plain = run_inloc_eval(
        EvalInLocConfig(output_root=os.path.join(root, "m0"), **kw),
        model_config=TINY, params=params, progress=False)

    # run 1: every entry corrupted the moment it commits → the SECOND run
    # detects every corruption on read, quarantines, recomputes
    with faults.injected(faults.FaultPlan(store_bitflip_paths=(".feat",))):
        run_inloc_eval(
            EvalInLocConfig(output_root=os.path.join(root, "m1"),
                            feature_store_dir=sd, **kw),
            model_config=TINY, params=params, progress=False)
    corrupted = run_inloc_eval(
        EvalInLocConfig(output_root=os.path.join(root, "m2"),
                        feature_store_dir=sd,
                        telemetry_dir=os.path.join(root, "t2"), **kw),
        model_config=TINY, params=params, progress=False)

    # run 2: ENOSPC-shaped I/O errors on read and write → pure recompute
    with faults.injected(faults.FaultPlan(
            store_io_error_ops=("read", "write"))):
        degraded = run_inloc_eval(
            EvalInLocConfig(output_root=os.path.join(root, "m3"),
                            feature_store_dir=sd,
                            telemetry_dir=os.path.join(root, "t3"), **kw),
            model_config=TINY, params=params, progress=False)

    a = _matches(plain)
    for out in (corrupted, degraded):
        got = _matches(out)
        assert sorted(got) == sorted(a)
        for name in a:
            np.testing.assert_array_equal(got[name], a[name])

    _, ev2 = replay_events(os.path.join(root, "t2", "events.jsonl"))
    st2 = [e for e in ev2 if e["event"] == "eval_summary"][-1]["store"]
    assert st2["counters"]["corrupt"] == 4  # every poisoned entry caught
    qdir = os.path.join(sd, "quarantine")
    assert len(os.listdir(qdir)) == 4

    _, ev3 = replay_events(os.path.join(root, "t3", "events.jsonl"))
    st3 = [e for e in ev3 if e["event"] == "eval_summary"][-1]["store"]
    assert st3["state"] == "DEGRADED"
    assert [e["state"] for e in ev3 if e["event"] == "store_health"][:1] \
        == ["DEGRADED"]


def test_spatial_shards_disable_store(tmp_path):
    """feature_store_dir under spatial sharding must warn + bypass (the
    sharded forward takes images), not crash or silently shard-skew."""
    root, params, kw = _inloc_fixture(tmp_path, n_queries=1, n_panos=1)
    out = run_inloc_eval(
        EvalInLocConfig(output_root=os.path.join(root, "m"),
                        feature_store_dir=os.path.join(root, "fstore"),
                        spatial_shards=2, **kw),
        model_config=TINY, params=params, progress=False)
    assert os.path.exists(os.path.join(out, "1.mat"))
    # the store was never opened: no generation dir appeared
    assert not os.path.exists(os.path.join(root, "fstore"))


# ---------------------------------------------------------------------------
# bulk builder tool
# ---------------------------------------------------------------------------


def test_build_feature_store_tool_resumable_and_eval_warm(tmp_path):
    """The offline builder populates the store a later eval reads 100%
    warm (same fingerprint, same bytes); a rerun fast-forwards via the
    shard manifest without recomputing anything."""
    import build_feature_store as bfs

    root, params, kw = _inloc_fixture(tmp_path)
    sd = os.path.join(root, "fstore")
    args = ["--store_dir", sd, "--inloc_shortlist", kw["inloc_shortlist"],
            "--pano_path", kw["pano_path"], "--backbone", "tiny",
            "--image_size", "128", "--k_size", "2", "--n_panos", "2"]
    assert bfs.main(args) == 0
    manifest = json.load(open(os.path.join(
        sd, "build_manifest.shard0_of_1.json")))
    assert len(manifest["completed"]) == 4
    assert not manifest["quarantined"]

    # rerun: resumable — every pano skipped via the manifest
    assert bfs.main(args) == 0

    # the eval over the tool-built store starts warm: zero misses.  The
    # tool inits its trunk from key(1) + backbone 'tiny' — exactly the
    # fixture's params — and the fingerprint hashes ONLY the trunk, so
    # the generations line up by construction.
    warm = run_inloc_eval(
        EvalInLocConfig(output_root=os.path.join(root, "m"),
                        feature_store_dir=sd,
                        telemetry_dir=os.path.join(root, "t"), **kw),
        model_config=TINY, params=params, progress=False)
    assert sorted(_matches(warm)) == ["1.mat", "2.mat"]
    _, events = replay_events(os.path.join(root, "t", "events.jsonl"))
    st = [e for e in events if e["event"] == "eval_summary"][-1]["store"]
    assert st["counters"]["hits"] == 4 and st["counters"]["misses"] == 0


def test_build_tool_quarantines_bad_pano_and_exits_2(tmp_path):
    """A pano that fails every decode attempt quarantines into the shard
    manifest (exit 2) while the rest of the stripe builds."""
    import build_feature_store as bfs

    root, params, kw = _inloc_fixture(tmp_path)
    sd = os.path.join(root, "fstore")
    args = ["--store_dir", sd, "--inloc_shortlist", kw["inloc_shortlist"],
            "--pano_path", kw["pano_path"], "--backbone", "tiny",
            "--image_size", "128", "--k_size", "2", "--n_panos", "2",
            "--retries", "1", "--retry_backoff_s", "0"]
    with faults.injected(faults.FaultPlan(
            decode_fail_substring="cutout_000_30")):
        assert bfs.main(args) == 2
    manifest = json.load(open(os.path.join(
        sd, "build_manifest.shard0_of_1.json")))
    assert len(manifest["quarantined"]) == 1
    assert len(manifest["completed"]) == 3
    # the rerun (fault cleared) completes the quarantined pano
    assert bfs.main(args) == 0
    manifest = json.load(open(os.path.join(
        sd, "build_manifest.shard0_of_1.json")))
    assert len(manifest["completed"]) == 4 and not manifest["quarantined"]


# ---------------------------------------------------------------------------
# serving plane: health section + metric families + watchdog advisory
# ---------------------------------------------------------------------------


def test_store_on_serving_health_metrics_and_watchdog(tmp_path, arr):
    """A service with a store attached surfaces it on /healthz (the store
    section) and /metrics (ncnet_store_* families); a DEGRADED store is an
    operator warning, and the stall watchdog's advisory NEVER flips a
    verdict to stalled over it."""
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import stall_watchdog

    from ncnet_tpu.observability.export import parse_prometheus, render
    from ncnet_tpu.serving import MatchService, ServingConfig
    from ncnet_tpu.serving.introspect import metrics_families, render_statusz
    from test_serving import FakeEngine

    store = _store(tmp_path)
    d = content_digest(arr)
    store.resolve(d, lambda: arr)
    store.resolve(d, lambda: arr)
    svc = MatchService(engine=FakeEngine(),
                       serving=ServingConfig(bucket_multiple=32,
                                             max_image_side=128),
                       store=store)
    svc.start()
    try:
        doc = svc.health()
        assert doc["store"]["state"] == "OK"
        assert doc["store"]["hit_pct"] == 50.0
        fams = parse_prometheus(render(metrics_families(svc)))
        assert fams["ncnet_store_up"]["samples"][0][2] == 1
        assert fams["ncnet_store_hits_total"]["samples"][0][2] == 1
        assert fams["ncnet_store_misses_total"]["samples"][0][2] == 1
        assert "feature store: OK" in render_statusz(svc)

        # degrade the store (read AND write failing, so the in-resolve
        # rewrite cannot recover it): /healthz carries it, ncnet_store_up
        # drops, and the watchdog advisory stays NON-stalling
        with faults.injected(faults.FaultPlan(
                store_io_error_ops=("read", "write"))):
            store.resolve(d, lambda: arr)
        doc = svc.health()
        assert doc["store"]["state"] == "DEGRADED"
        fams = parse_prometheus(render(metrics_families(svc)))
        assert fams["ncnet_store_up"]["samples"][0][2] == 0
        verdict = {"status": "alive"}
        stall_watchdog._apply_store_advisory(verdict, doc)
        assert verdict["status"] == "alive"
        assert verdict["store"]["state"] == "DEGRADED"
        # belt and braces: even a hypothetical stalled verdict is not
        # MADE stalled by the advisory (it never touches status)
        verdict = {"status": "stalled"}
        stall_watchdog._apply_store_advisory(verdict, doc)
        assert verdict["status"] == "stalled"
    finally:
        svc.stop()


def test_run_report_store_section(tmp_path, arr, capsys):
    """run_report --store replays the store's event stream: counters,
    the DEGRADED → recovered timeline, and the quarantined entries."""
    import run_report

    events_path = str(tmp_path / "events.jsonl")
    sink = EventLog(events_path)
    prev = obs_events.set_global_sink(sink)
    try:
        s = _store(tmp_path)
        d = content_digest(arr)
        with faults.injected(faults.FaultPlan(store_bitflip_paths=(d,))):
            s.put(d, arr)
        s.resolve(d, lambda: arr)  # corrupt -> quarantine -> recompute
        with faults.injected(faults.FaultPlan(store_io_error_ops=("write",))):
            s.resolve(content_digest(arr + 1), lambda: arr + 1)
        s.resolve(content_digest(arr + 1), lambda: arr + 1)  # recovers
        s.flush_stats()
        s.close()
    finally:
        obs_events.set_global_sink(prev)
        sink.close()

    report = run_report.build_report([events_path])
    st = report["store"]
    assert st["degraded_spells"] == 1 and st["recovered"] == 1
    assert len(st["corrupt_quarantined"]) == 1
    final = st["final_stats"]["store"]
    assert final["counters"]["corrupt"] == 1

    assert run_report.main([events_path, "--store"]) == 0
    out = capsys.readouterr().out
    assert "feature store" in out
    assert "DEGRADED" in out and "corrupt" in out
