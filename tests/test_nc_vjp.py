"""Resident Pallas NC-stack backward: interpret-mode grad parity, routing,
tier registry, and the training-path composition (round 7).

The kernel design notes live in ops/nc_fused_lane_vjp.py.  Two test-harness
decisions worth their docstrings:

* **Reference = XLA autodiff over the same bf16 VALUES upcast to f32.**
  The fused VJP accumulates in f32 (dots and dW/db accumulators); XLA's
  bf16 autodiff accumulates bias gradients in bf16, whose reduction error
  measured 60× LARGER than ours against an f64 ground truth (4.875 vs
  4.406 against a true 4.399 on the first debug case).  Upcasting the
  reference removes ITS noise while keeping identical operand values, so
  the comparison measures our kernels, not the reference's rounding.

* **ReLU-margin construction.**  The backward recomputes activations
  in-kernel, so its masks are ``bf16-rounded z > 0`` of the REPLAYED
  forward — at cells where |z| is within bf16 drift of 0 (~1e-2 at unit
  scale) the replay and the reference can disagree, flipping a whole
  cotangent cell (observed: 1 flip per 625 cells on random data → ~0.15
  spurious "error").  That is inherent to every recompute-based backward
  (any remat with a different formulation has it) and harmless in
  training, where the mask is self-consistent with the fused forward the
  loss actually ran.  The parity tests construct networks with a
  structural margin instead: each layer's bias is shifted so the widest
  near-zero gap of its per-channel pre-activation histogram straddles the
  boundary, keeping every |z| above the drift.
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ncnet_tpu.config import ModelConfig
from ncnet_tpu.ops.conv4d import conv4d
from ncnet_tpu.ops import nc_fused_lane_vjp as vjp_mod
from ncnet_tpu.ops.nc_fused_lane import (
    _ALL_TIERS,
    demote_fused_tier,
    demoted_fused_tiers,
    nc_stack_fused,
    reset_fused_tier_demotions,
)
from ncnet_tpu.ops.nc_fused_lane_vjp import (
    choose_fused_vjp,
    fused_vjp_feasible,
    nc_stack_fused_vjp,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def xla_stack(params, x):
    for layer in params:
        x = jax.nn.relu(conv4d(x, layer["w"], layer["b"]))
    return x


def ref_vjp_f32(params, x, g):
    """XLA autodiff over the same bf16 values upcast to f32 (see module
    docstring: removes the reference's own bf16 reduction noise)."""
    p32 = jax.tree.map(lambda t: t.astype(jnp.float32), params)
    _, vjp = jax.vjp(lambda pp, xx: xla_stack(pp, xx), p32,
                     x.astype(jnp.float32))
    return vjp(g.astype(jnp.float32))


def margin_params(key, kernels, channels, x, min_margin=2e-3):
    """Random bf16 stack with a structural ReLU margin: per layer, shift
    each output channel's bias so the widest near-zero gap of its
    pre-activation histogram is centered on the boundary (see module
    docstring)."""
    params, c_in = [], 1
    cur = x
    for k, c_out in zip(kernels, channels):
        k1, k2, key = jax.random.split(key, 3)
        layer = {
            "w": jax.random.normal(k1, (k,) * 4 + (c_in, c_out),
                                   jnp.bfloat16) * 0.1,
            "b": jax.random.normal(k2, (c_out,), jnp.bfloat16) * 0.1,
        }
        z = np.asarray(conv4d(cur, layer["w"], layer["b"]), np.float32)
        deltas = []
        for c in range(c_out):
            zs = np.sort(z[..., c].ravel())
            win = zs[(zs > -0.8) & (zs < 0.8)]
            gaps = np.diff(win)
            i = int(np.argmax(gaps))
            deltas.append(-(win[i] + win[i + 1]) / 2)
        layer["b"] = (layer["b"].astype(jnp.float32)
                      + jnp.asarray(deltas, jnp.float32)).astype(jnp.bfloat16)
        z = conv4d(cur, layer["w"], layer["b"])
        margin = float(jnp.min(jnp.abs(z.astype(jnp.float32))))
        assert margin > min_margin, (
            f"margin construction failed ({margin:.2e}): pick another seed"
        )
        cur = jax.nn.relu(z)
        params.append(layer)
        c_in = c_out
    return params


def assert_grads_close(got, ref, atol=3e-2):
    """Per-tensor comparison scaled by the reference's max magnitude (the
    same normalization the forward parity tests use)."""
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(ref)):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        scale = max(1e-6, float(np.max(np.abs(b))))
        np.testing.assert_allclose(a / scale, b / scale, atol=atol)


@pytest.mark.parametrize("shape,kernels,channels", [
    ((2, 5, 5, 5, 5), (3, 3), (4, 1)),        # square 2-layer, batch 2
    ((1, 5, 5, 5, 5), (5, 5, 5), (4, 4, 1)),  # the 5⁴ PF-Pascal k=5 class
    ((1, 5, 4, 6, 5), (3, 3, 3), (4, 4, 1)),  # rectangular 3-layer
    ((1, 5, 5, 5, 5), (1, 1), (3, 1)),        # k=1 degenerate (no rings)
    ((2, 5, 6, 4, 7), (3, 3), (4, 2)),        # 2-ch final (tap-swap chain)
    ((1, 6, 6, 6, 6), (3,), (1,)),            # single layer
])
def test_grad_parity(shape, kernels, channels):
    """Interpret-mode fused VJP == XLA autodiff (f32-upcast reference) on
    every stack shape class: locks the staged wavefront schedule, the ring
    protocols, the in-kernel mask replay, the dW lane-shift contraction,
    and the flipped/transposed dX packing."""
    x = (jax.random.normal(jax.random.key(100), shape + (1,)) * 0.5
         ).astype(jnp.bfloat16)
    params = margin_params(jax.random.key(1), kernels, channels, x)
    out = xla_stack(params, x)
    g = (jax.random.normal(jax.random.key(9), out.shape) * 0.5
         ).astype(jnp.bfloat16)
    dp_ref, dx_ref = ref_vjp_f32(params, x, g)
    dp, dx = nc_stack_fused_vjp(params, x, g, interpret=True)
    assert dx.dtype == x.dtype
    assert_grads_close((dp, dx), (dp_ref, dx_ref))


def test_custom_vjp_routes_through_pallas_backward(monkeypatch):
    """jax.vjp THROUGH nc_stack_fused (the registered custom_vjp) with the
    force knob set must run the resident Pallas backward — asserted by
    spying the dispatcher the rule calls — and match XLA grads."""
    monkeypatch.setenv("NCNET_FUSED_VJP_FORCE", "interpret")
    calls = []
    real = vjp_mod.nc_stack_fused_vjp

    def spy(params, x, g, interpret=False):
        calls.append(interpret)
        return real(params, x, g, interpret=interpret)

    monkeypatch.setattr(vjp_mod, "nc_stack_fused_vjp", spy)

    x = (jax.random.normal(jax.random.key(4), (1, 5, 5, 5, 5, 1)) * 0.5
         ).astype(jnp.bfloat16)
    params = margin_params(jax.random.key(3), (3,), (1,), x)
    out_f, vjp_f = jax.vjp(nc_stack_fused, params, x)
    d_fused = vjp_f(jnp.ones_like(out_f))
    assert calls == [True]  # the Pallas chain ran (interpret-forced)
    d_ref = ref_vjp_f32(params, x, jnp.ones_like(out_f))
    assert_grads_close(d_fused, d_ref)


def test_tier_registry_resident_vjp():
    """'resident_vjp' is demotable by NAME only: the default (eval) ladder
    still walks resident → perlayer, and an explicitly demoted backward
    tier is skipped by choose_fused_vjp even where probes are green."""
    reset_fused_tier_demotions()
    try:
        assert "resident_vjp" in _ALL_TIERS
        # default ladder untouched: eval recovery still demotes the
        # forward tiers in the PR 3 order
        assert demote_fused_tier() == "resident"
        assert demote_fused_tier() == "perlayer"
        assert demote_fused_tier() is None
        assert "resident_vjp" not in demoted_fused_tiers()
        # by-name demotion of the backward tier
        assert demote_fused_tier("resident_vjp") == "resident_vjp"
        assert demote_fused_tier("resident_vjp") is None  # already demoted
        assert "resident_vjp" in demoted_fused_tiers()
    finally:
        reset_fused_tier_demotions()


def test_choose_fused_vjp_honors_demotion(monkeypatch):
    """With a Pallas backend and green probes (all monkeypatched), a
    demoted 'resident_vjp' sends the chooser to None — the XLA-replay
    backward — mirroring the forward tiers' runtime-degradation
    contract."""
    import importlib

    # the ops package re-exports the conv4d FUNCTION under the submodule's
    # name, so attribute-style module import resolves to the function
    c4 = importlib.import_module("ncnet_tpu.ops.conv4d")

    monkeypatch.setattr(c4, "_pallas_available", lambda: True)
    monkeypatch.setattr(vjp_mod, "fused_vjp_feasible",
                        lambda *a: True)
    monkeypatch.setattr(vjp_mod, "fused_vjp_compiles",
                        lambda *a: True)
    reset_fused_tier_demotions()
    try:
        args = (25, 25, 25, 25, (5, 5, 5), (16, 16, 1))
        assert choose_fused_vjp(*args) == "resident_vjp"
        assert demote_fused_tier("resident_vjp") == "resident_vjp"
        assert choose_fused_vjp(*args) is None
    finally:
        reset_fused_tier_demotions()


def test_choose_fused_vjp_is_none_on_cpu():
    assert choose_fused_vjp(25, 25, 25, 25, (5, 5, 5), (16, 16, 1)) is None


def test_vjp_feasibility_gate():
    """Shape-class + per-stage VMEM gate: the PF-Pascal and IVD training
    shapes pass; InLoc-scale volumes, mixed/even kernels, and wide final
    layers are rejected (same classes the resident forward rejects)."""
    assert fused_vjp_feasible(25, 25, 25, 25, (5, 5, 5), (16, 16, 1))
    assert fused_vjp_feasible(13, 13, 13, 13, (3, 3), (16, 1))
    # tap-swap block-diagonal chain class
    assert fused_vjp_feasible(13, 17, 13, 17, (3, 3), (32, 2))
    assert not fused_vjp_feasible(100, 75, 150, 200, (3, 3), (16, 1))
    assert not fused_vjp_feasible(25, 25, 25, 25, (5, 3, 5), (16, 16, 1))
    assert not fused_vjp_feasible(25, 25, 25, 25, (4, 4, 4), (16, 16, 1))
    assert not fused_vjp_feasible(25, 25, 25, 25, (5, 5), (16, 16))


# ---------------------------------------------------------------------------
# the training path: weak_loss / weak_loss_and_grads routing + composition
# ---------------------------------------------------------------------------

TINY16 = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                     ncons_channels=(1,), half_precision=True)


def _tiny_batch(b=2, hw=48, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "source_image": jnp.asarray(
            rng.uniform(-1, 1, (b, hw, hw, 3)).astype(np.float32)),
        "target_image": jnp.asarray(
            rng.uniform(-1, 1, (b, hw, hw, 3)).astype(np.float32)),
    }


def _tiny_params(seed=0):
    import warnings

    from ncnet_tpu.models import init_ncnet

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        params = init_ncnet(TINY16, jax.random.key(seed))
    # shift the NC biases off zero: a random-init net on a mutual-matched
    # volume has most pre-activations AT the ReLU boundary (the volume is
    # mostly near-zero cells and conv4d_init biases are zero), where the
    # recompute-based backward's masks legitimately differ from XLA's by
    # bf16 rounding — the module docstring's margin argument, applied to
    # the composed-loss tests
    params["nc"] = [
        {"w": layer["w"], "b": layer["b"] + 0.05}
        for layer in params["nc"]
    ]
    return params


def test_weak_loss_keeps_xla_path_without_force():
    """The no-regression guard: on a backend with no Pallas (and no force
    knob) the r7 default ``nc_pallas_vjp=True`` must be a bit-exact no-op
    against the explicit XLA path."""
    from ncnet_tpu.training.loss import weak_loss

    params = _tiny_params()
    batch = _tiny_batch()
    a = weak_loss(TINY16, params, batch, nc_pallas_vjp=True)
    b = weak_loss(TINY16, params, batch, nc_pallas_vjp=False)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_weak_loss_and_grads_route_through_fused_vjp(monkeypatch):
    """With the force knob set, weak_loss's value_and_grad AND the chunked
    weak_loss_and_grads route the filter through the fused stack whose
    backward is the Pallas chain (spy-asserted), across the unfolded,
    fold_pos_neg, and accum-chunked forms — and all three agree with the
    XLA-path gradients."""
    from ncnet_tpu.training.loss import weak_loss, weak_loss_and_grads

    params = _tiny_params()
    batch = _tiny_batch()

    def nc_grads(fn):
        loss, grads = fn()
        return float(loss), grads["nc"] if isinstance(grads, dict) else grads

    def vg(**kw):
        def f():
            return jax.value_and_grad(
                lambda p: weak_loss(TINY16, p, batch,
                                    stop_backbone_grad=True, **kw)
            )(params)
        return f

    # the XLA reference (force off)
    monkeypatch.setenv("NCNET_FUSED_VJP_FORCE", "off")
    loss_ref, g_ref = nc_grads(vg(nc_pallas_vjp=False))

    monkeypatch.setenv("NCNET_FUSED_VJP_FORCE", "interpret")
    calls = []
    real = vjp_mod.nc_stack_fused_vjp

    def spy(p, x, g, interpret=False):
        calls.append(x.shape)
        return real(p, x, g, interpret=interpret)

    monkeypatch.setattr(vjp_mod, "nc_stack_fused_vjp", spy)

    for label, fn in [
        ("unfolded", vg()),
        ("fold_pos_neg", vg(fold_pos_neg=True)),
        ("accum_chunks", lambda: weak_loss_and_grads(
            TINY16, params, batch, accum_chunks=2)),
    ]:
        calls.clear()
        loss, g_nc = nc_grads(fn)
        assert calls, f"{label}: the Pallas VJP chain never ran"
        assert abs(loss - loss_ref) < 3e-2, label
        # weight grads: f32-accumulated on both sides — tight.  Bias grads:
        # the XLA reference reduces them in bf16, whose noise measured 60×
        # OURS against an f64 ground truth (module docstring) — the loose
        # bar is the reference's, not the kernel's; exact db parity is
        # locked by test_grad_parity against the f32-upcast reference.
        assert_grads_close(
            [layer["w"] for layer in g_nc],
            [layer["w"] for layer in g_ref], atol=3e-2)
        assert_grads_close(
            [layer["b"] for layer in g_nc],
            [layer["b"] for layer in g_ref], atol=2e-1)


def test_train_step_device_error_demotes_vjp_tier_and_continues(tmp_path):
    """The training twin of the eval loops' tier degradation: an injected
    runtime device failure on the first train-step dispatch demotes
    'resident_vjp' FIRST (not the eval forward ladder), re-traces, retries
    off-budget, and the run completes with states bitwise-identical to a
    clean run (on CPU both execute the XLA stack; the demotion is
    registry-visible)."""
    from ncnet_tpu.config import TrainConfig
    from ncnet_tpu.data.synthetic import write_pair_dataset
    from ncnet_tpu import ops, training
    from ncnet_tpu.utils import faults
    from ncnet_tpu.utils.faults import FaultPlan

    root = str(tmp_path / "data")
    write_pair_dataset(root, n_pairs=4, image_hw=(48, 48), shift=(16, 16),
                       seed=1)

    def cfg(out_dir):
        return TrainConfig(
            model=TINY16, image_size=48,
            dataset_image_path=root, dataset_csv_path=root + "/image_pairs",
            num_epochs=1, batch_size=2, lr=1e-3,
            result_model_dir=str(out_dir), log_interval=10,
            data_parallel=False,
        )

    ops.reset_fused_tier_demotions()
    try:
        clean = training.fit(cfg(tmp_path / "clean"), progress=False)
        assert ops.demoted_fused_tiers() == frozenset()
        with faults.injected(FaultPlan(device_fail_calls=(1,))):
            faulty = training.fit(cfg(tmp_path / "faulty"), progress=False)
        assert ops.demoted_fused_tiers() == {"resident_vjp"}
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            clean["state"].params, faulty["state"].params,
        )
        assert int(faulty["state"].step) == int(clean["state"].step)
    finally:
        ops.reset_fused_tier_demotions()


def test_kill_mid_step_resume_bitwise_identical_on_fused_vjp(tmp_path):
    """PR 1's acceptance property on the NEW training path: SIGKILL a
    training subprocess mid-checkpoint with the fused Pallas VJP forced
    (interpret), resume, and the finished run must match an uninterrupted
    twin bitwise (params, opt_state, step) — proving the r7 backward kept
    checkpoint/resume determinism."""
    import json

    from ncnet_tpu.config import TrainConfig
    from ncnet_tpu.data.synthetic import write_pair_dataset
    from ncnet_tpu.models import checkpoint as ckpt_io
    from ncnet_tpu import training

    root = str(tmp_path / "data")
    write_pair_dataset(root, n_pairs=4, image_hw=(48, 48), shift=(16, 16),
                       seed=1)

    def cfg(out_dir, **kw):
        base = dict(
            model=TINY16, image_size=48,
            dataset_image_path=root, dataset_csv_path=root + "/image_pairs",
            num_epochs=1, batch_size=2, lr=1e-3,
            result_model_dir=str(out_dir), log_interval=10,
            data_parallel=False, checkpoint_steps=1, keep_checkpoints=10,
        )
        base.update(kw)
        return TrainConfig(**base)

    worker = tmp_path / "worker.py"
    worker.write_text(f"""
import sys
sys.path.insert(0, {_REPO!r})
import jax
jax.config.update("jax_platforms", "cpu")
from ncnet_tpu.config import ModelConfig, TrainConfig
from ncnet_tpu import training

cfg = TrainConfig(
    model=ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                      ncons_channels=(1,), half_precision=True),
    image_size=48,
    dataset_image_path={root!r},
    dataset_csv_path={root + "/image_pairs"!r},
    num_epochs=1, batch_size=2, lr=1e-3,
    result_model_dir={str(tmp_path / "killed")!r},
    log_interval=10, data_parallel=False,
    checkpoint_steps=1, keep_checkpoints=10,
)
training.fit(cfg, progress=False)
""")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["NCNET_FUSED_VJP_FORCE"] = "interpret"
    env["NCNET_TPU_FAULTS"] = json.dumps({"kill_at_version": 2})
    proc = subprocess.run(
        [sys.executable, str(worker)], env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=600,
    )
    assert proc.returncode == -9, f"expected SIGKILL:\n{proc.stdout[-3000:]}"

    (ckpt_root,) = [
        os.path.join(tmp_path / "killed", d)
        for d in os.listdir(tmp_path / "killed")
    ]
    assert [n for n, _ in ckpt_io.list_checkpoint_versions(ckpt_root)] == [1]

    # resume + the uninterrupted twin, both on the forced fused-VJP path
    os.environ["NCNET_FUSED_VJP_FORCE"] = "interpret"
    try:
        r_resumed = training.fit(
            cfg(tmp_path / "killed",
                model=TINY16.replace(checkpoint=ckpt_root)),
            progress=False,
        )
        r_full = training.fit(cfg(tmp_path / "full"), progress=False)
    finally:
        del os.environ["NCNET_FUSED_VJP_FORCE"]
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        r_resumed["state"].params, r_full["state"].params,
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        r_resumed["state"].opt_state, r_full["state"].opt_state,
    )
    assert int(r_resumed["state"].step) == int(r_full["state"].step) == 2
