"""Chaos suite for the live model rollout (ncnet_tpu/serving/rollout.py).

The ISSUE 18 acceptance bars, executed deterministically through the
utils/faults.py rollout hooks against FakeEngine pools (the replica-level
seams are real — serving/replica.py, serving/service.py — so the fake
engine exercises the REAL drain/swap/readmit/judge paths):

  (a) sustained stream against a 4-replica pool → canaried old->new
      rollout COMPLETEs with ZERO lost requests, ready capacity never
      observed below N-1, every phase/swap/verdict replayable via
      ``run_report --rollout``, and the durable pointer advanced;
  (b) an injected canary quality regression (``canary_quality_shift``)
      breaches the PSI drift gate → automatic ROLLED_BACK, the pod back
      on the old params AND version, pointer never advanced;
  (c) a bit-rotted candidate (``corrupt_candidate_checkpoint`` through
      the REAL versioned-checkpoint loader) is refused at staging by the
      payload-sha256 gate BEFORE any replica is touched;
  (d) SIGKILL mid-swap (``kill_at_weight_swap`` in a subprocess) leaves
      the two-phase pointer un-advanced → the restart resolves the OLD
      checkpoint: one consistent version, never a mix;
  (e) the multi-host router keeps routing a mixed-version pod mid-rollout
      and says so (``pod.model_versions``);
  (f) the wire control plane (POST/GET /rollout) + tools/rollout.py exit
      codes 0 (COMPLETE) / 2 (ROLLED_BACK) / 1 (refused), 409 on a
      concurrent rollout, 400 on a bad request.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request
import warnings

import numpy as np
import pytest

from ncnet_tpu import ops
from ncnet_tpu.observability import EventLog
from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.serving import (
    READY,
    REPLICA_READY,
    BatchMatchEngine,
    MatchRouter,
    MatchService,
    Overloaded,
    RouterConfig,
    ServingConfig,
)
from ncnet_tpu.serving.rollout import (
    ROLLOUT_CANARY,
    ROLLOUT_COMPLETE,
    ROLLOUT_IDLE,
    ROLLOUT_PROMOTING,
    ROLLOUT_ROLLED_BACK,
    ROLLOUT_STAGING,
    RolloutConfig,
    RolloutController,
    read_rollout_state,
    resolve_serving_checkpoint,
    write_rollout_state,
)
from ncnet_tpu.store import FeatureStore, content_digest
from ncnet_tpu.utils import faults
from ncnet_tpu.utils.faults import FaultPlan

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import rollout as rollout_tool  # noqa: E402
import run_report  # noqa: E402
import stall_watchdog  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_state():
    """No armed faults, no demoted tiers, no leaked event sink."""
    faults.clear()
    ops.reset_fused_tier_demotions()
    obs_events.set_global_sink(None)
    yield
    faults.clear()
    ops.reset_fused_tier_demotions()
    obs_events.set_global_sink(None)


def u8(side=32, seed=0):
    return np.random.default_rng(seed).integers(
        0, 255, (side, side, 3), dtype=np.uint8)


class FakeEngine:
    """Device stand-in (same protocol as tests/test_serving_pool.py) plus
    the rollout's ``swap_params`` seam — the drain/swap/warmup/readmit
    ladder runs through the REAL Replica/MatchService code either way."""

    split = staticmethod(BatchMatchEngine.split)
    half_precision = False

    def __init__(self, latency_s: float = 0.0):
        self.latency_s = latency_s
        self.swapped = []  # every params object this engine was given

    def dispatch(self, src, tgt):
        faults.device_error_hook("fake_serve")
        return (src.shape[0], time.monotonic())

    def fetch(self, handle):
        b, t0 = handle
        while time.monotonic() - t0 < self.latency_s:
            time.sleep(0.005)
        table = np.zeros((b, 6, 16), np.float32)
        table[:, 4, :] = 1.0
        table[:, 5, :5] = [0.5, 0.1, 0.4, 0.9, 0.8]
        return table

    def retrace(self):
        pass

    def swap_params(self, params):
        self.swapped.append(params)


def pool_service(n=4, latency_s=0.02, **over):
    cfg = dict(bucket_multiple=32, max_image_side=64, max_batch=2,
               replica_max_failures=1, resurrect_after_s=0.2,
               model_version="v0",
               # a single-client chaos stream: the fairness cap must
               # exceed the stream depth or the tests shed themselves
               max_queue=128, max_in_flight_per_client=128)
    cfg.update(over)
    engines = [FakeEngine(latency_s=latency_s) for _ in range(n)]
    svc = MatchService(engine=engines,
                       serving=ServingConfig(**cfg)).start()
    # injected-engine services carry no real params; give rollback a
    # recognizable old-params object to restore
    svc._model_params = "params-v0"
    return svc, engines


def wait_until(pred, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def drive_stream(svc, ctl, *, max_wall_s=60.0):
    """Submit one repeated pair against ``svc`` until ``ctl`` reaches a
    terminal phase; returns (futures, shed_at_submit, min_ready_seen).
    Overloaded at submit is ELASTIC admission doing its job while a
    replica is drained — classified, never a crash."""
    pair = (u8(seed=1), u8(seed=2))
    futs, shed, min_ready = [], 0, 10 ** 9
    deadline = time.monotonic() + max_wall_s
    while ctl.status()["phase"] not in (ROLLOUT_COMPLETE,
                                        ROLLOUT_ROLLED_BACK, ROLLOUT_IDLE):
        assert time.monotonic() < deadline, \
            f"rollout stuck in {ctl.status()['phase']}"
        try:
            futs.append(svc.submit(*pair))
        except Overloaded as e:
            shed += 1
            time.sleep(min(e.retry_after_s or 0.05, 0.2))
        min_ready = min(min_ready, svc.health()["pool"]["ready"])
        time.sleep(0.005)
    return futs, shed, min_ready


def settle(futs, timeout=60.0):
    """Resolve every admitted future; returns (results, failures)."""
    results = failures = 0
    for f in futs:
        try:
            f.result(timeout=timeout)
            results += 1
        except Exception:  # noqa: BLE001 — classified failure, not lost
            failures += 1
    return results, failures


# ---------------------------------------------------------------------------
# units: durable pointer, state machine edges, watchdog advisory
# ---------------------------------------------------------------------------


def test_rollout_state_pointer_two_phase(tmp_path):
    """``current`` only advances at COMPLETE: a candidate-only state file
    (the SIGKILL-mid-swap residue) still resolves to the default."""
    path = str(tmp_path / "rollout_state.json")
    assert resolve_serving_checkpoint(path, "/ckpt/old") == "/ckpt/old"
    assert resolve_serving_checkpoint(None, "/ckpt/old") == "/ckpt/old"
    write_rollout_state(path, {"current": None, "candidate": "/ckpt/new",
                               "phase": "STAGING"})
    st = read_rollout_state(path)
    assert st["candidate"] == "/ckpt/new" and st["schema"] == 1
    assert resolve_serving_checkpoint(path, "/ckpt/old") == "/ckpt/old"
    write_rollout_state(path, {"current": "/ckpt/new",
                               "candidate": "/ckpt/new",
                               "phase": "COMPLETE"})
    assert resolve_serving_checkpoint(path, "/ckpt/old") == "/ckpt/new"
    # a truncated/garbage state file degrades to the default, never raises
    with open(path, "w") as f:
        f.write("{nope")
    assert resolve_serving_checkpoint(path, "/ckpt/old") == "/ckpt/old"


def test_rollout_illegal_transition_raises():
    svc, _ = pool_service(n=2, latency_s=0.0)
    try:
        ctl = RolloutController(svc, RolloutConfig())
        with pytest.raises(RuntimeError, match="illegal rollout transition"):
            ctl._to(ROLLOUT_PROMOTING)  # IDLE -> PROMOTING is not an edge
    finally:
        svc.stop(drain=False)


def test_stall_watchdog_rollout_advisory_is_not_liveness():
    """The watchdog's model section: rollout phase + mixed versions are
    surfaced as advisory context, never a liveness verdict."""
    verdict = {"ok": True}
    doc = {
        "model_version": "v0",
        "rollout": {"phase": "CANARY", "old_version": "v0",
                    "new_version": "v1", "reason": None},
        "pool": {"ready": 3, "total": 4, "replicas": [
            {"id": "rep0", "model_version": "v1"},
            {"id": "rep1", "model_version": "v0"},
        ]},
    }
    stall_watchdog._apply_rollout_advisory(verdict, doc)
    m = verdict["model"]
    assert m["rollout"]["phase"] == "CANARY"
    assert m["rollout"]["new_version"] == "v1"
    assert m["mixed_versions"] == ["v0", "v1"]
    assert verdict["ok"] is True  # advisory only — liveness untouched


def test_feature_store_gc_keeps_rollback_generation(tmp_path):
    """``gc_superseded(keep_generations=1)`` spares the most-recently-
    touched superseded WEIGHTS generation — the rollback target's cache
    stays warm through promotion."""
    arr = u8(seed=3).astype(np.float32)
    d = content_digest(arr)
    root = str(tmp_path / "fstore")
    now = time.time()
    for i, fp in enumerate(["aaaa1111-s64-k2-f32", "bbbb2222-s64-k2-f32",
                            "cccc3333-s64-k2-f32"]):
        s = FeatureStore(root, fp)
        s.put(d, arr)
        s.close()
        # stagger mtimes so "newest superseded" is unambiguous
        os.utime(os.path.join(root, fp), (now - 100 + i, now - 100 + i))
    cur = FeatureStore(root, "dddd4444-s64-k2-f32")
    cur.put(d, arr)
    assert cur.gc_superseded(keep_generations=1) == 2
    left = sorted(n for n in os.listdir(root) if not n.startswith("quar"))
    assert left == ["cccc3333-s64-k2-f32", "dddd4444-s64-k2-f32"]
    # grace spent: the next swap's GC with 0 removes the survivor too
    assert cur.gc_superseded(keep_generations=0) == 1
    cur.close()


# ---------------------------------------------------------------------------
# (a) the promote chaos chain: stream -> canary -> rolling swaps -> COMPLETE
# ---------------------------------------------------------------------------


def test_rollout_promotes_under_stream_zero_lost(tmp_path, capsys):
    log_path = str(tmp_path / "events.jsonl")
    state_path = str(tmp_path / "rollout_state.json")
    with obs_events.bound(EventLog(log_path)):
        svc, engines = pool_service(n=4)
        svc.rollout_loader = lambda cand: (cand, "v1", None, "params-v1")
        try:
            ctl = svc.start_rollout("/ckpt/v1", RolloutConfig(
                canary_fraction=0.5, canary_min_results=4,
                canary_timeout_s=30.0, drain_timeout_s=10.0,
                state_path=state_path))
            futs, shed, min_ready = drive_stream(svc, ctl)
            wait_until(lambda: not svc._rollout_thread.is_alive())
            st = ctl.status()
            assert st["phase"] == ROLLOUT_COMPLETE
            # pod identity advanced; every replica converged on v1
            assert svc.model_version == "v1"
            assert all(r.model_version == "v1"
                       for r in svc.rollout_replicas())
            assert all(e.swapped == ["params-v1"] for e in engines)
            # ZERO lost: every admitted request resolves as a result
            results, failures = settle(futs)
            assert results == len(futs) and failures == 0
            assert results > 0  # the stream actually exercised the pod
            # capacity: ready never observed below N-1 (one drained swap
            # at a time)
            assert min_ready >= 3
            # the judge saw both versions and passed
            assert st["verdict"]["breach"] is None
            assert st["verdict"]["results"]["old"] >= 4
            assert st["verdict"]["results"]["new"] >= 4
            # per-version metric families split by construction
            metrics = svc.metrics()
            assert metrics.get("version_results_v1", 0) > 0
            assert metrics.get("version_results_v0", 0) > 0
            # the durable pointer advanced at COMPLETE (phase 2)
            assert resolve_serving_checkpoint(state_path, "(old)") \
                == "/ckpt/v1"
        finally:
            svc.stop()

    # -- replay: the event log alone reconstructs the whole rollout ------
    _, events = obs_events.replay_events(log_path)
    phases = [e["phase"] for e in events
              if e.get("event") == "rollout_phase"]
    assert phases == [ROLLOUT_STAGING, ROLLOUT_CANARY,
                      ROLLOUT_PROMOTING, ROLLOUT_COMPLETE]
    sec = run_report.build_rollout_section(events)
    assert sec["terminal_phase"] == "COMPLETE"
    assert len(sec["swaps"]) == 4 and sec["swaps_failed"] == 0
    assert all(s["ok"] and s["version"] == "v1" for s in sec["swaps"])
    assert not sec["refusals"] and not sec["rollbacks"]
    assert sec["canary_verdicts"][0]["breach"] is None
    # version-tagged accounting: both versions served during the window
    assert sec["versions"]["v0"]["results"] > 0
    assert sec["versions"]["v1"]["results"] > 0
    assert sec["versions"]["v0"]["failures"] == 0
    assert sec["versions"]["v1"]["failures"] == 0
    # the serving section agrees on the mixed-version window
    serving = run_report.build_serving_section(events)
    assert sorted(serving["results_by_version"]) == ["v0", "v1"]

    # -- the CLI rendering (run_report --rollout) ------------------------
    assert run_report.main([log_path, "--rollout"]) == 0
    out = capsys.readouterr().out
    assert "-> COMPLETE" in out and "[v0 -> v1]" in out
    assert "weight swaps (4, 0 failed)" in out
    assert "canary verdict [pass]" in out


# ---------------------------------------------------------------------------
# (b) injected canary regression -> automatic rollback
# ---------------------------------------------------------------------------


def test_canary_quality_shift_triggers_auto_rollback(tmp_path):
    log_path = str(tmp_path / "events.jsonl")
    state_path = str(tmp_path / "rollout_state.json")
    with obs_events.bound(EventLog(log_path)):
        svc, engines = pool_service(n=4)
        svc.rollout_loader = lambda cand: (cand, "v1", None, "params-v1")
        try:
            with faults.injected(FaultPlan(canary_quality_shift=0.4,
                                           canary_shift_version="v1")):
                ctl = svc.start_rollout("/ckpt/v1", RolloutConfig(
                    canary_fraction=0.5, canary_min_results=4,
                    canary_timeout_s=30.0, drain_timeout_s=10.0,
                    state_path=state_path))
                futs, _, min_ready = drive_stream(svc, ctl)
                wait_until(lambda: not svc._rollout_thread.is_alive())
            st = ctl.status()
            assert st["phase"] == ROLLOUT_ROLLED_BACK
            assert st["verdict"]["breach"].startswith("quality_drift:")
            # the pod is back on the OLD version and the OLD params
            assert svc.model_version == "v0"
            assert all(r.model_version == "v0"
                       for r in svc.rollout_replicas())
            # exactly one engine saw the canary swap, then swapped back
            touched = [e for e in engines if e.swapped]
            assert len(touched) == 1
            assert touched[0].swapped == ["params-v1", "params-v0"]
            # rollback lost nothing either
            results, failures = settle(futs)
            assert results == len(futs) and failures == 0
            assert min_ready >= 3
            # the pointer NEVER advanced: a restart lands on the old ckpt
            assert resolve_serving_checkpoint(state_path, "(old)") \
                == "(old)"
            st_file = read_rollout_state(state_path)
            assert st_file["current"] is None
            assert st_file["phase"] == ROLLOUT_ROLLED_BACK
        finally:
            svc.stop()

    _, events = obs_events.replay_events(log_path)
    sec = run_report.build_rollout_section(events)
    assert sec["terminal_phase"] == "ROLLED_BACK"
    assert sec["rollbacks"][0]["reason"].startswith("quality_drift:")
    assert not sec["rollbacks"][0].get("stuck_replicas")
    verdict = sec["canary_verdicts"][0]
    # the PSI evidence is in the replayed verdict, not just a summary
    drifted = [s for s, v in verdict["psi"].items()
               if v > verdict["psi_threshold"]]
    assert drifted


# ---------------------------------------------------------------------------
# (c) corrupt candidate refused at staging by the REAL checkpoint loader
# ---------------------------------------------------------------------------


def test_corrupt_candidate_refused_before_any_replica(tmp_path):
    jax = pytest.importorskip("jax")
    from ncnet_tpu import models
    from ncnet_tpu.config import ModelConfig
    from ncnet_tpu.models.checkpoint import save_params

    cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                      ncons_channels=(1,))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        params = models.init_ncnet(cfg, jax.random.key(0))
    root = tmp_path / "ckpts"
    save_params(str(root / "step_000050"), cfg, params)

    log_path = str(tmp_path / "events.jsonl")
    svc, engines = pool_service(n=2, latency_s=0.0)
    try:
        with obs_events.bound(EventLog(log_path)), \
                faults.injected(FaultPlan(
                    corrupt_candidate_checkpoint="step_000050")):
            ctl = RolloutController(svc, RolloutConfig(
                state_path=str(tmp_path / "state.json")))
            # the DEFAULT loader: newest-complete resolution + sha gate
            assert ctl.run(str(root)) == ROLLOUT_IDLE
        st = ctl.status()
        assert st["reason"] == "refused:payload_sha_mismatch"
        # no replica was touched: no swaps, everything still READY v0
        assert all(e.swapped == [] for e in engines)
        assert all(r.state == REPLICA_READY and r.model_version == "v0"
                   for r in svc.rollout_replicas())
        assert svc.health()["state"] == READY
        # refusal leaves no durable residue at all
        assert resolve_serving_checkpoint(
            str(tmp_path / "state.json"), "(old)") == "(old)"
    finally:
        svc.stop(drain=False)
    _, events = obs_events.replay_events(log_path)
    ref = [e for e in events if e.get("event") == "rollout_refused"]
    assert len(ref) == 1 and ref[0]["reason"] == "payload_sha_mismatch"


# ---------------------------------------------------------------------------
# (d) SIGKILL mid-swap: the restart resolves ONE consistent (old) version
# ---------------------------------------------------------------------------


_KILL_CHILD = """
import sys, time
sys.path.insert(0, {repo!r})
import numpy as np
from ncnet_tpu.serving import BatchMatchEngine, MatchService, ServingConfig
from ncnet_tpu.serving.rollout import RolloutConfig, RolloutController

class FakeEngine:
    split = staticmethod(BatchMatchEngine.split)
    half_precision = False
    def dispatch(self, src, tgt):
        return (src.shape[0], time.monotonic())
    def fetch(self, handle):
        b, _ = handle
        t = np.zeros((b, 6, 16), np.float32)
        t[:, 4, :] = 1.0
        return t
    def retrace(self):
        pass
    def swap_params(self, params):
        pass

svc = MatchService(
    engine=[FakeEngine(), FakeEngine()],
    serving=ServingConfig(bucket_multiple=32, max_image_side=32,
                          max_batch=1, model_version="v0")).start()
ctl = RolloutController(
    svc, RolloutConfig(state_path=sys.argv[1], canary_min_results=0),
    loader=lambda cand: (cand, "v1", None, "params-v1"))
ctl.run("/ckpt/new")  # NCNET_TPU_FAULTS kills us inside the first swap
sys.stdout.write("SURVIVED\\n")  # must never be reached
"""


def test_sigkill_mid_swap_recovers_on_old_version(tmp_path):
    state_path = str(tmp_path / "rollout_state.json")
    child = tmp_path / "child.py"
    child.write_text(_KILL_CHILD.format(repo=_REPO))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               NCNET_TPU_PERF_STORE="off", NCNET_TPU_TIER_CACHE="off",
               NCNET_TPU_FAULTS='{"kill_at_weight_swap": 1}')
    proc = subprocess.run(
        [sys.executable, str(child), state_path],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert proc.returncode == -signal.SIGKILL, \
        (proc.returncode, proc.stdout[-2000:], proc.stderr[-2000:])
    assert "SURVIVED" not in proc.stdout
    # phase 1 ran (the candidate is recorded) but phase 2 never did:
    # the restart resolves the OLD checkpoint — one consistent version
    st = read_rollout_state(state_path)
    assert st is not None and st["candidate"] == "/ckpt/new"
    assert st.get("current") is None
    assert resolve_serving_checkpoint(state_path, "/ckpt/old") \
        == "/ckpt/old"


# ---------------------------------------------------------------------------
# (e) the router keeps routing a mixed-version pod and says so
# ---------------------------------------------------------------------------


def test_router_reports_mixed_version_pod(tmp_path):
    svc_a, _ = pool_service(n=2, latency_s=0.0, introspect_port=0)
    svc_b, _ = pool_service(n=2, latency_s=0.0, introspect_port=0)
    router = None
    try:
        assert svc_a.introspect_url and svc_b.introspect_url
        ctl = RolloutController(
            svc_b, RolloutConfig(canary_min_results=0),
            loader=lambda cand: (cand, "v1", None, "params-v1"))
        assert ctl.run("/ckpt/v1") == ROLLOUT_COMPLETE  # promote blind
        assert svc_b.model_version == "v1"
        router = MatchRouter(
            [svc_a.introspect_url, svc_b.introspect_url],
            RouterConfig(probe_period_s=0.1, resurrect_after_s=0.3,
                         backend_max_failures=2)).start()
        assert wait_until(
            lambda: router.health()["pod"]["model_versions"] == ["v0", "v1"])
        # a mixed-version pod still serves through the router
        fut = router.submit(u8(seed=4), u8(seed=5))
        assert fut.result(timeout=60).table is not None
    finally:
        if router is not None:
            router.stop()
        svc_a.stop(drain=False)
        svc_b.stop(drain=False)


# ---------------------------------------------------------------------------
# (f) wire control plane + tools/rollout.py exit codes
# ---------------------------------------------------------------------------


def test_wire_control_plane_and_cli_exit_codes(tmp_path, capsys):
    svc, _ = pool_service(n=2, latency_s=0.0, introspect_port=0)
    try:
        base = svc.introspect_url
        assert base

        # GET /rollout before any rollout: the IDLE doc
        with urllib.request.urlopen(base + "/rollout", timeout=10) as r:
            doc = json.loads(r.read().decode("utf-8"))
        assert doc == {"phase": "IDLE", "model_version": "v0"}

        # POST with no checkpoint key -> 400
        req = urllib.request.Request(
            base + "/rollout", data=b'{"not_checkpoint": 1}',
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400

        # exit 0: a full POST+watch to COMPLETE through the CLI
        svc.rollout_loader = lambda cand: (cand, "v1", None, "params-v1")
        rc = rollout_tool.main([base, "/ckpt/v1", "--canary-min-results",
                                "0", "--poll", "0.05", "--timeout", "60"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "-> COMPLETE" in out
        assert wait_until(lambda: svc.model_version == "v1")

        # 409: a second rollout while one is in flight; then exit 2 via
        # --watch on the starved canary's automatic rollback
        svc.rollout_loader = lambda cand: (cand, "v2", None, "params-v2")
        code, doc = rollout_tool.post_rollout(
            base, "/ckpt/v2",
            {"canary_min_results": 4, "canary_timeout_s": 1.0})
        assert code == 202 and doc["phase"] in ("IDLE", "STAGING", "CANARY")
        code2, doc2 = rollout_tool.post_rollout(base, "/ckpt/v2", {})
        assert code2 == 409 and "in progress" in doc2["error"]
        rc = rollout_tool.main([base, "--watch", "--poll", "0.05",
                                "--timeout", "60", "--json"])
        out = capsys.readouterr().out
        assert rc == 2, out
        assert "ROLLED_BACK (canary_starved)" in out
        assert wait_until(lambda: svc.model_version == "v1")  # restored

        # exit 1: a refused candidate (same version) terminates IDLE
        wait_until(lambda: not svc._rollout_thread.is_alive())
        svc.rollout_loader = lambda cand: (cand, "v1", None, "params-v1")
        rc = rollout_tool.main([base, "/ckpt/v1", "--poll", "0.05",
                                "--timeout", "60"])
        out = capsys.readouterr().out
        assert rc == 1, out
        assert "IDLE" in out and "refused:same_version" in out
    finally:
        svc.stop(drain=False)


# ---------------------------------------------------------------------------
# tools: the real-model probe smoke (the full checkpoint path on CPU)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_serve_probe_rollout_tiny_smoke(capsys):
    import serve_probe

    rc = serve_probe.main(["--rollout", "--tiny", "--sides", "48",
                           "--pairs", "4"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)["rollout"]
    assert doc["phase"] == "COMPLETE"
    assert doc["lost"] == 0
    assert doc["min_ready_replicas"] >= 1  # N-1 for the 2-replica pool
    assert doc["pod_version"] == doc["new_version"]
    assert doc["resolved_checkpoint"].endswith(doc["new_version"])
