"""Spatially-sharded volume forward vs. the unsharded path (8-device CPU mesh).

The parity bar: every sharded stage must reproduce the single-device program
bit-for-bit up to float-reduction tolerance — halo exchange must equal 'same'
zero padding at the global edges, pmax must equal the full-B max, and the
relocalization delta bookkeeping must survive sharding.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu import parallel
from ncnet_tpu.config import ModelConfig
from ncnet_tpu.models.ncnet import init_ncnet, ncnet_filter, ncnet_forward
from ncnet_tpu.ops import correlation_4d


def _mesh(data, spatial):
    return parallel.make_mesh(data=data, spatial=spatial,
                              devices=jax.devices()[: data * spatial])


def _volume_cfg(**kw):
    defaults = dict(backbone="tiny", ncons_kernel_sizes=(5, 3),
                    ncons_channels=(6, 1))
    defaults.update(kw)
    return ModelConfig(**defaults)


@pytest.mark.parametrize("data,spatial", [(1, 8), (2, 4)])
def test_spatial_filter_parity_rectangular(rng, data, spatial):
    """Rectangular InLoc-like volume, no relocalization: sharded filter ==
    unsharded filter.  hB=16 → local shards of 2 (spatial=8) or 4 (spatial=4),
    both ≥ the kernel-5 halo of 2."""
    cfg = _volume_cfg()
    params = init_ncnet(cfg, jax.random.key(0))
    corr = jnp.asarray(rng.standard_normal((data, 5, 7, 16, 6)).astype(np.float32))
    mesh = _mesh(data, spatial)
    ref = ncnet_filter(cfg, params, corr).corr
    got = jax.jit(
        lambda p, c: parallel.spatial_filter(cfg, p, c, mesh).corr
    )(params, corr)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_spatial_filter_parity_with_relocalization(rng):
    """k=2 maxpool4d relocalization under sharding: pooled volume AND all
    four delta offset grids must match the unsharded path exactly."""
    cfg = _volume_cfg(relocalization_k_size=2)
    params = init_ncnet(cfg, jax.random.key(1))
    # fine grid hB=16 → pooled 8 → 4 shards of 2
    corr = jnp.asarray(rng.standard_normal((1, 6, 8, 16, 12)).astype(np.float32))
    mesh = _mesh(1, 4)
    ref = ncnet_filter(cfg, params, corr)
    got = jax.jit(
        lambda p, c: parallel.spatial_filter(cfg, p, c, mesh)
    )(params, corr)
    np.testing.assert_allclose(np.asarray(got.corr), np.asarray(ref.corr),
                               rtol=2e-5, atol=2e-5)
    for g, r in zip(got.delta4d, ref.delta4d):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(r))


def test_spatial_filter_parity_asymmetric(rng):
    """symmetric_mode=False exercises the hB-only halo path."""
    cfg = _volume_cfg(symmetric_mode=False)
    params = init_ncnet(cfg, jax.random.key(2))
    corr = jnp.asarray(rng.standard_normal((1, 4, 5, 16, 7)).astype(np.float32))
    mesh = _mesh(1, 8)
    ref = ncnet_filter(cfg, params, corr).corr
    got = jax.jit(
        lambda p, c: parallel.spatial_filter(cfg, p, c, mesh).corr
    )(params, corr)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_spatial_correlation_parity(rng):
    fa = jnp.asarray(rng.standard_normal((2, 5, 7, 16)).astype(np.float32))
    fb = jnp.asarray(rng.standard_normal((2, 8, 6, 16)).astype(np.float32))
    mesh = _mesh(2, 4)
    ref = correlation_4d(fa, fb)
    got = jax.jit(lambda a, b: parallel.spatial_correlation(a, b, mesh))(fa, fb)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_spatial_forward_parity_end_to_end(rng):
    """Images → features → sharded correlation → sharded filter must equal
    the plain ncnet_forward, including bf16 half-precision handling."""
    cfg = _volume_cfg(half_precision=True, relocalization_k_size=2)
    params = init_ncnet(cfg, jax.random.key(3))
    src = jnp.asarray(rng.uniform(-1, 1, (1, 96, 128, 3)).astype(np.float32))
    tgt = jnp.asarray(rng.uniform(-1, 1, (1, 256, 128, 3)).astype(np.float32))
    mesh = _mesh(1, 4)
    ref = ncnet_forward(cfg, params, src, tgt)
    got = jax.jit(
        lambda p, s, t: parallel.spatial_forward(cfg, p, s, t, mesh)
    )(params, src, tgt)
    np.testing.assert_allclose(
        np.asarray(got.corr, dtype=np.float32),
        np.asarray(ref.corr, dtype=np.float32),
        rtol=2e-2, atol=2e-2,  # bf16 volume
    )
    for g, r in zip(got.delta4d, ref.delta4d):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(r))


def test_spatial_filter_rejects_unshardable_hb(rng):
    """Pad-and-mask relaxed the divisibility gate; what remains rejected:
    fine hB not a multiple of k (ragged pooling window), and volumes whose
    post-pad shards are thinner than the conv halo."""
    params = init_ncnet(_volume_cfg(), jax.random.key(0))
    # k=2 with odd fine hB: pooling would mix real and pad rows
    cfg_k2 = _volume_cfg(relocalization_k_size=2)
    corr = jnp.asarray(rng.standard_normal((1, 4, 4, 7, 4)).astype(np.float32))
    with pytest.raises(ValueError, match="spatial shards"):
        parallel.spatial_filter(cfg_k2, params, corr, _mesh(1, 4))
    # kernel-5 halo of 2 > post-pad shard height of 1
    cfg = _volume_cfg()
    corr = jnp.asarray(rng.standard_normal((1, 4, 4, 8, 4)).astype(np.float32))
    with pytest.raises(ValueError, match="spatial shards"):
        parallel.spatial_filter(cfg, params, corr, _mesh(1, 8))


@pytest.mark.parametrize("spatial,hb", [(4, 10), (8, 20)])
def test_spatial_filter_parity_padded_hb(rng, spatial, hb):
    """Pad-and-mask parity (VERDICT r3 item 2): an hB that does NOT divide
    the shard count must still reproduce the unsharded filter exactly —
    pad rows are masked out of the mutual-matching maxes and re-zeroed
    after every conv, and the output is sliced back to the true hB."""
    cfg = _volume_cfg()
    params = init_ncnet(cfg, jax.random.key(4))
    assert hb % spatial != 0  # the case the gate used to reject
    corr = jnp.asarray(
        rng.standard_normal((1, 5, 7, hb, 6)).astype(np.float32)
    )
    mesh = _mesh(1, spatial)
    ref = ncnet_filter(cfg, params, corr).corr
    got = jax.jit(
        lambda p, c: parallel.spatial_filter(cfg, p, c, mesh).corr
    )(params, corr)
    assert got.shape == ref.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_spatial_filter_parity_padded_hb_relocalization(rng):
    """The canonical-InLoc shape class: k=2 relocalization with fine hB not
    dividing n_shards·k (20 % (8·2) != 0 → pad to 32, pooled 10 valid)."""
    cfg = _volume_cfg(relocalization_k_size=2)
    params = init_ncnet(cfg, jax.random.key(5))
    corr = jnp.asarray(rng.standard_normal((1, 6, 8, 20, 12)).astype(np.float32))
    mesh = _mesh(1, 8)
    ref = ncnet_filter(cfg, params, corr)
    got = jax.jit(
        lambda p, c: parallel.spatial_filter(cfg, p, c, mesh)
    )(params, corr)
    assert got.corr.shape == ref.corr.shape
    np.testing.assert_allclose(np.asarray(got.corr), np.asarray(ref.corr),
                               rtol=2e-5, atol=2e-5)
    for g, r in zip(got.delta4d, ref.delta4d):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(r))


def test_spatial_filter_parity_padded_hb_three_layer(rng):
    """Padded hB through the transposed symmetric pass (3 layers are not
    tap-swap-fusable): pad rows must be re-zeroed along the volume's
    LEADING dim in the transposed stack too."""
    cfg = _volume_cfg(ncons_kernel_sizes=(3, 3, 3), ncons_channels=(4, 4, 1))
    params = init_ncnet(cfg, jax.random.key(6))
    corr = jnp.asarray(rng.standard_normal((1, 5, 7, 10, 6)).astype(np.float32))
    mesh = _mesh(1, 4)
    ref = ncnet_filter(cfg, params, corr).corr
    got = jax.jit(
        lambda p, c: parallel.spatial_filter(cfg, p, c, mesh).corr
    )(params, corr)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_spatial_forward_parity_padded_end_to_end(rng):
    """Images whose target feature rows don't divide the shard count: the
    features are zero-row padded pre-correlation and the result must still
    equal the plain forward (incl. output shape)."""
    cfg = _volume_cfg(relocalization_k_size=2)
    params = init_ncnet(cfg, jax.random.key(8))
    src = jnp.asarray(rng.uniform(-1, 1, (1, 96, 128, 3)).astype(np.float32))
    # 320 px → 20 feature rows: 20 % (4·2) != 0 → pad-and-mask path
    tgt = jnp.asarray(rng.uniform(-1, 1, (1, 320, 128, 3)).astype(np.float32))
    mesh = _mesh(1, 4)
    ref = ncnet_forward(cfg, params, src, tgt)
    got = jax.jit(
        lambda p, s, t: parallel.spatial_forward(cfg, p, s, t, mesh)
    )(params, src, tgt)
    assert got.corr.shape == ref.corr.shape
    np.testing.assert_allclose(np.asarray(got.corr), np.asarray(ref.corr),
                               rtol=2e-5, atol=2e-5)
    for g, r in zip(got.delta4d, ref.delta4d):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(r))


@pytest.mark.slow
def test_spatial_filter_scaling_sanity(rng):
    """8-shard vs unsharded wall-clock on the SAME volume, on the virtual CPU
    mesh.  All 8 virtual devices share one host CPU, so the sharded wall is
    total-work + collective overhead; this bounds the overhead (halo
    exchanges, pmax, bookkeeping) at ≤3x total work — a known-good
    expectation to carry to the first real multi-chip rig, where the work
    term divides by 8 (VERDICT r2 item 8).  Numerical parity is asserted by
    the tests above; this one only guards against a pathological collective
    or relayout explosion in the sharded program.
    """
    import time

    cfg = _volume_cfg()
    params = init_ncnet(cfg, jax.random.key(3))
    corr = jnp.asarray(rng.standard_normal((1, 12, 12, 32, 24)).astype(np.float32))
    mesh = _mesh(1, 8)

    ref_fn = jax.jit(lambda p, c: ncnet_filter(cfg, p, c).corr)
    shard_fn = jax.jit(lambda p, c: parallel.spatial_filter(cfg, p, c, mesh).corr)

    def wall(fn, n=3):
        fn(params, corr).block_until_ready()  # compile
        times = []
        for _ in range(n):
            t0 = time.perf_counter()
            fn(params, corr).block_until_ready()
            times.append(time.perf_counter() - t0)
        return min(times)

    t_ref = wall(ref_fn)
    t_shard = wall(shard_fn)
    # generous bound: virtual devices serialize the work, so the ratio is
    # (1x work + overhead) / 1x work; 3x means overhead ≤ 2x compute.
    assert t_shard < 3.0 * t_ref + 0.05, (t_shard, t_ref)


def test_spatial_filter_parity_three_layer_transpose_form(rng):
    """3-layer stacks are NOT tap_swap_fusable, so this pins the sharded
    transposed-pass fallback (halo exchange along the volume's leading dim,
    axis 1) that every 2-layer config now bypasses."""
    from ncnet_tpu.models.ncnet import tap_swap_fusable

    cfg = _volume_cfg(ncons_kernel_sizes=(3, 3, 3), ncons_channels=(4, 4, 1))
    params = init_ncnet(cfg, jax.random.key(7))
    assert not tap_swap_fusable(params["nc"])
    corr = jnp.asarray(rng.standard_normal((1, 5, 7, 16, 6)).astype(np.float32))
    mesh = _mesh(1, 4)
    ref = ncnet_filter(cfg, params, corr).corr
    got = jax.jit(
        lambda p, c: parallel.spatial_filter(cfg, p, c, mesh).corr
    )(params, corr)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
