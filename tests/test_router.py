"""Chaos suite for the multi-host match routing tier (ISSUE 12).

Three layers under test:

  * **Wire data plane** (``ncnet_tpu/serving/wire.py`` + ``POST /match``
    on the introspection server): versioned framing round trips, schema
    refusal, classified outcomes over HTTP, and deadline/client
    propagation into a real backend's admission control.
  * **Fronting router** (``ncnet_tpu/serving/router.py``): health-scored
    backend routing, off-budget failover across injected AND real process
    deaths, backend quarantine with wire-probe-gated resurrection,
    backpressure propagation with honest aggregate retry hints,
    coordinated drain in both directions, and elastic admission fed by
    pod replica units.
  * **Tools**: ``run_report`` router section (the outcome-total identity
    recomputed at the router level), ``stall_watchdog --url`` judging a
    router with the per-backend staleness breakdown, and the
    ``serve_probe --router`` pod sweep smoke.

THE acceptance chain (test_acceptance_chain_multihost): a 3-backend CPU
pod — real subprocesses — under a sustained stream survives SIGKILL of one
backend mid-batch with ZERO lost admitted requests, routes around it,
marks it DEAD, re-admits it after a probe succeeds on a restarted process
at the same address, surfaces backend backpressure with an aggregate
``retry_after_s``, proves an edge deadline expires as ``DeadlineExceeded``
(never a silent backend timeout), and SIGTERM on the router drains
everything clean — all recomputed from the event log.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from ncnet_tpu import ops
from ncnet_tpu.observability import EventLog
from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.serving import (
    BACKEND_DEAD,
    BACKEND_DRAINING,
    BACKEND_READY,
    DEGRADED,
    READY,
    STOPPED,
    AdmissionController,
    BatchMatchEngine,
    DeadlineExceeded,
    MatchClient,
    MatchRouter,
    MatchService,
    Overloaded,
    RequestQuarantined,
    RouterConfig,
    ServingConfig,
    WireError,
)
from ncnet_tpu.serving import wire
from ncnet_tpu.utils import faults
from ncnet_tpu.utils.faults import FaultPlan

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import run_report  # noqa: E402
import serve_probe  # noqa: E402
import stall_watchdog  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_state():
    faults.clear()
    ops.reset_fused_tier_demotions()
    obs_events.set_global_sink(None)
    yield
    faults.clear()
    ops.reset_fused_tier_demotions()
    obs_events.set_global_sink(None)


def u8(side=32, seed=0):
    return np.random.default_rng(seed).integers(
        0, 255, (side, side, 3), dtype=np.uint8)


class FakeEngine:
    """Device stand-in (tests/test_serving_pool.py protocol): the wire and
    router layers sit ABOVE the engine, so fake engines behind real
    services exercise every multi-host path with zero compiles."""

    split = staticmethod(BatchMatchEngine.split)
    half_precision = False

    def __init__(self, latency_s: float = 0.01):
        self.latency_s = latency_s

    def dispatch(self, src, tgt):
        faults.device_error_hook("fake_serve")
        return (src.shape[0], time.monotonic())

    def fetch(self, handle):
        b, t0 = handle
        while time.monotonic() - t0 < self.latency_s:
            time.sleep(0.005)
        table = np.zeros((b, 6, 16), np.float32)
        table[:, 4, :] = 1.0
        table[:, 5, :5] = [0.5, 0.1, 0.4, 0.9, 0.8]
        return table

    def retrace(self):
        pass


def wire_backend(n=2, latency_s=0.01, **over):
    """One in-process backend: a fake-engine MatchService with the live
    plane (incl. POST /match) on an ephemeral loopback port."""
    cfg = dict(bucket_multiple=32, max_image_side=64, max_batch=2,
               max_queue=64, max_in_flight_per_client=64,
               introspect_port=0)
    cfg.update(over)
    svc = MatchService(engine=[FakeEngine(latency_s) for _ in range(n)],
                       serving=ServingConfig(**cfg)).start()
    assert svc.introspect_url is not None
    return svc


def make_router(services, **over):
    cfg = dict(probe_period_s=0.2, resurrect_after_s=0.3,
               backend_max_failures=2, max_queue=256,
               max_in_flight_per_client=256)
    cfg.update(over)
    urls = [s if isinstance(s, str) else s.introspect_url
            for s in services]
    return MatchRouter(urls, RouterConfig(**cfg)).start()


def wait_until(pred, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# ---------------------------------------------------------------------------
# wire framing units
# ---------------------------------------------------------------------------


def test_wire_request_roundtrip_and_refusal():
    src, tgt = u8(seed=1), u8(seed=2)
    blob = wire.encode_request(src, tgt, client="cam0", budget_s=0.25,
                               request_id="r9")
    src2, tgt2, meta = wire.decode_request(blob)
    assert (src2 == src).all() and (tgt2 == tgt).all()
    # the clock-sync send stamp always rides; an untraced request still
    # decodes trace=None (the additive pod-trace field)
    assert isinstance(meta.pop("sent_t"), float)
    assert meta == {"client": "cam0", "budget_s": 0.25, "request": "r9",
                    "stream": None,  # untagged request: no stream session
                    "trace": None}
    # a peer speaking another wire schema is REFUSED, not misread: flip
    # the version byte and the decode must raise before trusting anything
    with pytest.raises(WireError, match="schema"):
        wire.decode_request(blob[:4] + bytes([wire.WIRE_SCHEMA + 1])
                            + blob[5:])
    with pytest.raises(WireError, match="magic"):
        wire.decode_request(b"XXXX" + blob[4:])
    with pytest.raises(WireError, match="payload"):
        wire.decode_request(blob[:-10])   # cut into the array bytes
    with pytest.raises(WireError, match="truncated"):
        wire.decode_request(blob[:16])    # cut into the header itself
    # garbage payload sizes are refused too
    hdr = {"src_shape": [4, 4, 3], "tgt_shape": [4, 4, 3],
           "dtype": "uint8", "client": "c", "budget_s": None,
           "request": ""}
    bad = wire._frame(hdr, b"\x00" * 7)
    with pytest.raises(WireError, match="payload"):
        wire.decode_request(bad)


def test_wire_response_outcomes_roundtrip():
    from ncnet_tpu.serving.request import MatchResult

    table = np.arange(60, dtype=np.float32).reshape(6, 10)
    status, blob = wire.encode_result(MatchResult(
        request_id="r1", table=table, quality={"q_score": 0.7},
        bucket=((32, 32), (64, 32)), wall_s=0.042))
    assert status == 200
    out = wire.decode_response(blob)
    assert (out.table == table).all()
    assert out.bucket == ((32, 32), (64, 32))
    assert out.quality == {"q_score": 0.7}
    assert out.wall_s == pytest.approx(0.042, abs=1e-5)
    # each error class survives the wire as ITSELF, fields intact
    status, blob = wire.encode_error(Overloaded(
        "full", reason="queue_full", retry_after_s=0.5))
    assert status == 429
    with pytest.raises(Overloaded) as e:
        wire.decode_response(blob)
    assert e.value.reason == "queue_full"
    assert e.value.retry_after_s == 0.5
    status, blob = wire.encode_error(DeadlineExceeded("late", where="fetch"))
    assert status == 504
    with pytest.raises(DeadlineExceeded) as e:
        wire.decode_response(blob)
    assert e.value.where == "fetch"
    status, blob = wire.encode_error(RequestQuarantined(
        "gone", kind="timeout", attempts=3))
    assert status == 500
    with pytest.raises(RequestQuarantined) as e:
        wire.decode_response(blob)
    assert e.value.kind == "timeout" and e.value.attempts == 3
    # an unclassified backend bug still encodes as a terminal outcome
    status, blob = wire.encode_error(ValueError("surprise"))
    assert status == 500
    with pytest.raises(RequestQuarantined):
        wire.decode_response(blob)


# ---------------------------------------------------------------------------
# the /match endpoint against a real (fake-engine) service
# ---------------------------------------------------------------------------


def test_match_endpoint_serves_and_classifies():
    svc = wire_backend(n=2)
    try:
        client = MatchClient(svc.introspect_url)
        img = u8()
        r = client.match(img, img, client="edge", budget_s=10.0,
                         request_id="e1")
        assert r.table.shape == (5, 16)
        assert r.quality and "score" in r.quality
        assert r.bucket == ((32, 32), (32, 32))
        # an already-expired propagated budget classifies at the BACKEND's
        # admission door and comes back as the same exception class
        with pytest.raises(DeadlineExceeded) as e:
            client.match(img, img, budget_s=-0.5)
        assert e.value.where == "admission"
        # the propagated client identity hits the backend's per-client cap
        # (client cap 64 shared with queue bound; use a dedicated tiny one)
        client.close()
    finally:
        svc.stop()
    # client-cap propagation proven against a dedicated tight service
    svc = wire_backend(n=1, latency_s=0.2, max_queue=32,
                       max_in_flight_per_client=1, max_batch=1)
    try:
        client = MatchClient(svc.introspect_url)
        img = u8()
        # two wire calls from the SAME edge client id: with cap 1, the
        # second must shed client_cap while one is in flight — run them
        # concurrently via a raw submit through a second connection
        import threading

        results = {}

        def call(tag):
            c2 = MatchClient(svc.introspect_url)
            try:
                c2.match(img, img, client="one-edge-client", budget_s=10.0)
                results[tag] = "result"
            except Overloaded as e:
                results[tag] = e.reason
            finally:
                c2.close()

        ts = [threading.Thread(target=call, args=(i,)) for i in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        assert "client_cap" in results.values(), results
        client.close()
    finally:
        svc.stop()


def test_match_endpoint_refuses_garbage():
    svc = wire_backend(n=1)
    try:
        req = urllib.request.Request(
            svc.introspect_url + "/match", data=b"not a wire frame",
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 400
        # the error body is still a classified wire outcome
        with pytest.raises(RequestQuarantined, match="unserviceable"):
            wire.decode_response(e.value.read())
        # GET on /match is not a thing; POST elsewhere is not a thing
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(urllib.request.Request(
                svc.introspect_url + "/metrics", data=b"x", method="POST"),
                timeout=10)
        assert e.value.code == 404
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# router: routing, accounting, failover, backpressure, deadlines, drain
# ---------------------------------------------------------------------------


def test_router_routes_and_recomputes_outcome_totals(tmp_path):
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        s1, s2 = wire_backend(), wire_backend()
        router = make_router([s1, s2])
        try:
            img = u8()
            futs = [router.submit(img, img, client="edge")
                    for _ in range(16)]
            for f in futs:
                f.result(timeout=60)
            assert all(f.outcome == "result" for f in futs)
            h = router.health()
            assert h["schema"] == 1 and h["role"] == "router"
            assert h["pod"]["ready"] == 2
            # both backends took traffic (two healthy equals, 16 requests)
            assert all(b.results >= 1 for b in router.backends)
        finally:
            router.stop()
            s1.stop()
            s2.stop()
    _, events = obs_events.replay_events(log_path)
    sec = run_report.build_router_section(events)
    assert sec["outcomes"]["admitted"] == 16
    assert sec["outcomes"]["results"] == 16
    assert sec["outcomes"]["unresolved"] == 0 and not sec["lost_requests"]
    assert set(sec["backends"]) == {"b0", "b1"}
    assert sum(b["results"] for b in sec["backends"].values()) == 16
    # the backend-reported wall rode the wire: fan-out overhead evidence
    assert all(b["backend_wall_ms"]["n"] == b["results"]
               for b in sec["backends"].values())
    assert sec["drains"] and sec["drains"][0]["drained"] is True
    # the renderer covers the router block end to end
    assert run_report.main([log_path, "--serving"]) == 0


def test_injected_backend_death_fails_over_and_resurrects(tmp_path):
    """The in-process twin of the process-kill chain: a backend whose wire
    dies must lose its traffic to the survivor off-budget, stay DEAD while
    broken (the /healthz control plane still answering must NOT resurrect
    it — resurrection is wire-probe gated), then rejoin after heal."""
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        s1, s2 = wire_backend(), wire_backend()
        router = make_router([s1, s2])
        try:
            img = u8()
            for f in [router.submit(img, img) for _ in range(6)]:
                f.result(timeout=60)
            faults.install(FaultPlan(
                dead_backend_urls=(s1.introspect_url,)))
            futs = [router.submit(img, img) for _ in range(12)]
            for f in futs:
                f.result(timeout=60)
            assert all(f.outcome == "result" for f in futs)
            b0 = router.backends[0]
            assert wait_until(lambda: b0.state == BACKEND_DEAD)
            assert router.state == DEGRADED
            # probes fire while armed — healthz is fine but the DATA plane
            # is not: the backend must STAY dead (no flapping)
            time.sleep(0.8)
            assert b0.state == BACKEND_DEAD
            # elastic admission shrank to the survivor's units
            h = router.health()
            assert h["queue"]["effective_max_queue"] < router.cfg.max_queue
            faults.clear()
            assert wait_until(lambda: b0.state == BACKEND_READY, 5)
            assert wait_until(lambda: router.state == READY, 5)
            futs = [router.submit(img, img) for _ in range(12)]
            for f in futs:
                f.result(timeout=60)
            assert b0.results >= 1  # it took traffic again
        finally:
            faults.clear()
            router.stop()
            s1.stop()
            s2.stop()
    _, events = obs_events.replay_events(log_path)
    reroutes = [e for e in events if e.get("event") == "retry"
                and e.get("scope") == "router"
                and e.get("via") == "reroute"]
    assert reroutes and all(e["backend"] == "b0" for e in reroutes)
    assert all(e["on_budget"] is False for e in reroutes)
    states = [(e.get("backend"), e.get("state")) for e in events
              if e.get("event") == "route_backend"]
    assert ("b0", BACKEND_DEAD) in states
    assert states.index(("b0", BACKEND_READY)) \
        > states.index(("b0", BACKEND_DEAD))
    sec = run_report.build_router_section(events)
    assert sec["outcomes"]["unresolved"] == 0
    assert sec["backends"]["b0"]["deaths"] == 1
    assert sec["backends"]["b0"]["resurrections"] == 1


def test_backpressure_propagates_with_aggregate_hint(tmp_path):
    """Backend ``Overloaded`` answers are NOT retried against the same
    host and NOT treated as failures: the router tries each live backend
    once, then surfaces ``Overloaded(reason="backpressure")`` with the
    soonest hint any backend promised."""
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        # tight backends: queue 2, slow engine — trivially saturated
        s1 = wire_backend(n=1, latency_s=0.5, max_queue=2, max_batch=1)
        s2 = wire_backend(n=1, latency_s=0.5, max_queue=2, max_batch=1)
        router = make_router([s1, s2])
        try:
            img = u8()
            # fill both backends at their own doors
            hold = []
            for s in (s1, s2):
                while True:
                    try:
                        hold.append(s.submit(img, img))
                    except Overloaded:
                        break
            f = router.submit(img, img)
            with pytest.raises(Overloaded) as e:
                f.result(timeout=30)
            assert e.value.reason == "backpressure"
            assert e.value.retry_after_s is not None
            assert f.outcome == "overloaded"
            b_shed = {b.id: b.backpressure for b in router.backends}
            assert all(n >= 1 for n in b_shed.values()), b_shed
            # neither backend saw a FAILURE for shedding (no death spiral)
            assert all(b.consecutive_failures == 0
                       for b in router.backends)
            assert all(b.state == BACKEND_READY for b in router.backends)
            for h in hold:
                try:
                    h.result(timeout=60)
                except Exception:  # noqa: BLE001 — draining the backlog
                    pass
        finally:
            router.stop()
            s1.stop()
            s2.stop()
    _, events = obs_events.replay_events(log_path)
    bp = [e for e in events if e.get("event") == "retry"
          and e.get("via") == "backpressure"]
    # exactly one backpressure bounce per live backend — never hammered
    assert sorted(e["backend"] for e in bp) == ["b0", "b1"]
    sheds = [e for e in events if e.get("event") == "route_shed"
             and e.get("admitted") is True]
    assert len(sheds) == 1 and sheds[0]["reason"] == "backpressure"
    assert isinstance(sheds[0]["retry_after_s"], float)


def test_edge_deadline_never_a_silent_timeout(tmp_path):
    """Deadline propagation end to end: a hung wire (injected pre-send
    stall) delivers the result AFTER the edge budget — the router must
    classify ``DeadlineExceeded``, never return the zombie success; and a
    budget that dies at the backend comes back naming the backend's
    checkpoint."""
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        s1 = wire_backend(n=2, latency_s=0.01)
        router = make_router([s1], retries=0)
        try:
            img = u8()
            # healthy first
            router.submit(img, img).result(timeout=30)
            # (a) budget expires INSIDE the backend (slow fetch vs budget):
            # classified by the backend with the propagated budget
            f = router.submit(img, img, deadline_s=0.001)
            with pytest.raises(DeadlineExceeded) as e:
                f.result(timeout=30)
            assert f.outcome == "deadline"
            # (b) the hung-wire shape: the send stalls past the budget,
            # the (eventual) result must be discarded as a deadline
            faults.install(FaultPlan(
                hang_backend_urls=(s1.introspect_url,),
                hang_backend_seconds=0.4))
            f = router.submit(img, img, deadline_s=0.15)
            with pytest.raises(DeadlineExceeded):
                f.result(timeout=30)
            assert f.outcome == "deadline"
            faults.clear()
        finally:
            faults.clear()
            router.stop()
            s1.stop()
    _, events = obs_events.replay_events(log_path)
    deadlines = [e for e in events if e.get("event") == "route_deadline"
                 and e.get("admitted") is not False]
    assert len(deadlines) == 2
    wheres = {e["where"] for e in deadlines}
    # each checkpoint is NAMED; none of them is a generic timeout
    assert wheres <= {"dequeue", "fetch", "backend_admission",
                      "backend_dequeue", "backend_fetch",
                      "backend_failure", "backpressure"}, wheres
    sec = run_report.build_router_section(events)
    assert sec["outcomes"]["unresolved"] == 0


def test_draining_backend_demoted_before_its_drain_completes():
    """Coordinated drain, backend side: a backend answering 503 DRAINING
    is demoted out of routing WITHOUT a failure streak while it finishes
    its admitted work; once it stops answering it is DEAD."""
    s1 = wire_backend(n=1, latency_s=0.2)
    s2 = wire_backend()
    router = make_router([s1, s2], probe_period_s=0.1)
    try:
        img = u8()
        for f in [router.submit(img, img) for _ in range(4)]:
            f.result(timeout=60)
        # park work on s1 so its drain takes a while, then drain it
        hold = [s1.submit(img, img) for _ in range(4)]
        s1.request_drain("rollout")
        b0 = router.backends[0]
        assert wait_until(lambda: b0.state == BACKEND_DRAINING, 5)
        assert b0.consecutive_failures == 0  # a drain is not a failure
        assert router.state == DEGRADED
        # traffic keeps flowing through the survivor only
        futs = [router.submit(img, img) for _ in range(6)]
        for f in futs:
            f.result(timeout=60)
        assert all(f.outcome == "result" for f in futs)
        assert router.backends[1].results >= 6
        for h in hold:
            h.result(timeout=60)  # the backend's drain completed its work
        s1.stop()  # now it is gone entirely
        assert wait_until(lambda: b0.state == BACKEND_DEAD, 5)
    finally:
        router.stop()
        s1.stop()
        s2.stop()


def test_router_drain_answers_503_and_completes_admitted_work():
    """Coordinated drain, router side: SIGTERM closes admission, the
    router's own /healthz answers 503 (a higher tier demotes it), and
    every admitted request still completes against the backends."""
    s1 = wire_backend(n=1, latency_s=0.15, max_batch=1)
    router = make_router([s1], introspect_port=0, install_sigterm=True)
    try:
        img = u8()
        futs = [router.submit(img, img) for _ in range(6)]
        os.kill(os.getpid(), signal.SIGTERM)
        # while draining: admission sheds, /healthz says 503
        assert wait_until(lambda: router.state == "DRAINING", 5)
        with pytest.raises(Overloaded) as e:
            router.submit(img, img)
        assert e.value.reason == "draining"
        try:
            with urllib.request.urlopen(router.introspect_url + "/healthz",
                                        timeout=5) as r:
                code = r.status
        except urllib.error.HTTPError as he:
            code = he.code
        assert code == 503
        for f in futs:
            assert f.result(timeout=60).request_id
        assert wait_until(lambda: router.state == STOPPED, 30)
    finally:
        router.stop()
        s1.stop()


def test_admission_units_track_pod_replica_capacity():
    """The capacity-units contract composes across tiers: the router's
    elastic bound follows the SUM of ready replicas across live backends
    (probe-document fed), not backend process counts — and the
    AdmissionController treats units identically for both tiers."""
    a = AdmissionController(max_queue=60, max_in_flight_per_client=64,
                            max_batch=1, dead_retry_after_s=1.5)
    # router-style: pod of 3 backends x 2 replicas = 6 units
    a.note_capacity(6, 6)
    assert a.effective_max_queue() == 60
    a.note_capacity(4, 6)   # one HOST (2 units) died
    assert a.effective_max_queue() == 40
    a.note_capacity(5, 6)   # one REPLICA on one host died — finer grain
    assert a.effective_max_queue() == 50
    a.note_capacity(0, 6)
    with pytest.raises(Overloaded) as e:
        a.admit("c", 0)
    assert e.value.reason == "no_capacity"
    assert e.value.retry_after_s == pytest.approx(1.5)
    # live: a router over a 2-replica backend advertises that backend's
    # units once the probe document lands
    s1 = wire_backend(n=2)
    router = make_router([s1], probe_period_s=0.1)
    try:
        assert wait_until(
            lambda: router.health()["pod"]["replicas_total"] == 2, 5)
        h = router.health()
        assert h["pod"]["replicas_ready"] == 2
        assert h["queue"]["effective_max_queue"] == router.cfg.max_queue
    finally:
        router.stop()
        s1.stop()


def test_routers_chain_as_wire_backends():
    """A router is itself a wire backend: a parent router fronting a
    sub-router must serve through it, accept the sub-router's
    ROUTER_DOC_SCHEMA health document (refusing neither shape), and
    ingest the sub-POD's replica units for scoring and admission."""
    s1 = wire_backend(n=2)
    child = make_router([s1], introspect_port=0, probe_period_s=0.1)
    parent = make_router([child.introspect_url], probe_period_s=0.1)
    try:
        img = u8()
        futs = [parent.submit(img, img) for _ in range(6)]
        for f in futs:
            assert f.result(timeout=60).table.shape == (5, 16)
        # the child's router document was ingested, not refused: the
        # parent's backend carries the sub-pod's replica units (2) and no
        # schema refusal / failure streak
        b0 = parent.backends[0]
        assert wait_until(lambda: b0.ready_replicas == 2, 5), \
            (b0.ready_replicas, b0.schema_refused)
        assert b0.schema_refused is False
        assert b0.consecutive_failures == 0
        h = parent.health()
        assert h["pod"]["replicas_ready"] == 2
    finally:
        parent.stop()
        child.stop()
        s1.stop()


# ---------------------------------------------------------------------------
# watchdog: the router verdict + per-backend staleness breakdown
# ---------------------------------------------------------------------------


def test_stall_watchdog_judges_router_with_backend_breakdown():
    s1 = wire_backend()
    router = make_router([s1], introspect_port=0)
    try:
        img = u8()
        for f in [router.submit(img, img) for _ in range(4)]:
            f.result(timeout=60)
        v = stall_watchdog.judge_url(router.introspect_url, factor=5,
                                     min_age=2.0)
        assert v["status"] == "alive" and v["role"] == "router"
        assert v["backends"]["b0"]["recent"] is True
    finally:
        router.stop()
        s1.stop()
    # the backstop itself: a stale aggregate with one fresh backend row
    # must read ALIVE via that backend — one wedged host cannot flag a
    # healthy pod (and with every row stale the verdict stays STALLED)
    doc = {"role": "router", "state": "READY",
           "activity": {"age_s": 120.0},
           "pod": {"backends": [
               {"id": "b0", "state": "READY", "ewma_wall_ms": 50.0,
                "last_result_age_s": 90.0},
               {"id": "b1", "state": "READY", "ewma_wall_ms": 50.0,
                "last_result_age_s": 0.4},
           ]}}
    verdict = {"status": "stalled"}
    stall_watchdog._apply_backend_backstop(verdict, doc, factor=5,
                                           min_age=2.0)
    assert verdict["status"] == "alive"
    assert verdict["alive_via"] == "backend_cadence:b1"
    assert verdict["backends"]["b0"]["recent"] is False
    verdict = {"status": "stalled"}
    doc["pod"]["backends"][1]["last_result_age_s"] = 80.0
    stall_watchdog._apply_backend_backstop(verdict, doc, factor=5,
                                           min_age=2.0)
    assert verdict["status"] == "stalled"


# ---------------------------------------------------------------------------
# THE acceptance chain: real processes, SIGKILL, restart-in-place, drain
# ---------------------------------------------------------------------------


def _spawn_backend(tmp_path, name, port=0, latency=0.08, max_queue=2):
    """One real backend process for the chain: one fake-engine replica,
    single-pair batches at ``latency`` each, and a TIGHT queue (so the
    backpressure phase can saturate a host's own admission door with a
    handful of competing direct clients — the continuous-batching pipeline
    absorbs a few in-flight batches before the queue even starts to
    build, so saturation needs sustained pressure, not a burst)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               NCNET_TPU_PERF_STORE="off", NCNET_TPU_TIER_CACHE="off")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_REPO, "tools", "serve_backend.py"),
         "--fake-engine", "--replicas", "1", "--latency", str(latency),
         "--port", str(port), "--max-queue", str(max_queue),
         "--max-batch", "1",
         "--events", str(tmp_path / f"{name}.jsonl")],
        stdout=subprocess.PIPE, text=True, env=env)
    doc = json.loads(proc.stdout.readline())
    return proc, doc["url"]


def test_acceptance_chain_multihost(tmp_path):
    """ISSUE 12 acceptance: 3 real backend processes, SIGKILL one
    mid-batch → zero lost, routed around, DEAD → restarted process at the
    SAME address re-admitted by a probe → backpressure surfaced with an
    aggregate hint → edge deadline classified → SIGTERM drains the router
    clean — the outcome-total identity recomputed from the event log."""
    log_path = str(tmp_path / "router_events.jsonl")
    procs = {}
    with obs_events.bound(EventLog(log_path)):
        for name in ("h0", "h1", "h2"):
            procs[name] = _spawn_backend(tmp_path, name)
        urls = [u for _, u in procs.values()]
        router = MatchRouter(urls, RouterConfig(
            probe_period_s=0.2, resurrect_after_s=0.3,
            backend_max_failures=2, retries=1, request_timeout_s=10.0,
            max_queue=512, max_in_flight_per_client=512,
            # depth 2 <= the backends' own queue bound, so the router's
            # normal pipeline never trips their doors — only the phase-5
            # direct-client competition does
            per_backend_depth=2,
            install_sigterm=True, introspect_port=0)).start()
        img = u8()
        try:
            # phase 1: healthy sustained stream across the pod
            futs = [router.submit(img, img) for _ in range(24)]
            for f in futs:
                f.result(timeout=120)
            assert all(f.outcome == "result" for f in futs)
            assert router.state == READY

            # phase 2: SIGKILL h1 mid-batch under load — zero lost
            p1, url1 = procs["h1"]
            victim = next(b for b in router.backends if b.url in url1)
            futs = [router.submit(img, img) for _ in range(24)]
            p1.kill()
            for f in futs:
                f.result(timeout=120)
            assert all(f.outcome == "result" for f in futs)
            assert wait_until(lambda: victim.state == BACKEND_DEAD, 15)
            assert router.state == DEGRADED
            survivors = [b for b in router.backends if b is not victim]
            assert all(b.state == BACKEND_READY for b in survivors)

            # phase 3: restart a NEW process at the SAME address; the
            # resurrection probe (healthz + wire probe) re-admits it
            port = int(url1.rsplit(":", 1)[1])
            p1.wait(timeout=10)
            procs["h1"] = _spawn_backend(tmp_path, "h1b", port=port)
            assert wait_until(lambda: victim.state == BACKEND_READY, 15)
            assert wait_until(lambda: router.state == READY, 5)
            futs = [router.submit(img, img) for _ in range(24)]
            for f in futs:
                f.result(timeout=120)
            assert wait_until(lambda: victim.results >= 1, 10)

            # phase 4: an edge deadline expires as DeadlineExceeded —
            # never a silent timeout, wherever the budget dies
            f = router.submit(img, img, deadline_s=0.002)
            with pytest.raises(DeadlineExceeded):
                f.result(timeout=30)
            assert f.outcome == "deadline"

            # phase 5: backend backpressure surfaced with an honest
            # aggregate hint — competing direct edge clients saturate
            # every host's own admission door (each occupier thread runs
            # its OWN MatchClient: the client is single-connection)
            import threading as _threading

            stop_sat = _threading.Event()

            def occupy(url):
                c = MatchClient(url)
                try:
                    while not stop_sat.is_set():
                        try:
                            c.match(img, img, client="sat", budget_s=30.0)
                        except Overloaded:
                            time.sleep(0.01)  # keep hammering the door
                        except Exception:  # noqa: BLE001 — saturation
                            return         # traffic, not the assertion
                finally:
                    c.close()

            occupiers = []
            for _, url in procs.values():
                # 16 competing clients per host: the pipeline absorbs ~4
                # in-flight single-pair batches, the tight queue (2) holds
                # 2 more — the rest keep every door saturated
                for _ in range(16):
                    t = _threading.Thread(target=occupy, args=(url,),
                                          daemon=True)
                    t.start()
                    occupiers.append(t)
            shed = None
            deadline_t = time.monotonic() + 30
            try:
                while shed is None and time.monotonic() < deadline_t:
                    f = router.submit(img, img)
                    try:
                        f.result(timeout=60)
                    except Overloaded as e:
                        shed = e
                    except Exception:  # noqa: BLE001 — other outcomes
                        pass
            finally:
                stop_sat.set()
                for t in occupiers:
                    t.join(60)
            assert shed is not None, "pod never propagated backpressure"
            assert shed.reason == "backpressure"
            assert shed.retry_after_s is not None

            # phase 6: SIGTERM on the router — coordinated drain, clean
            futs = [router.submit(img, img) for _ in range(8)]
            os.kill(os.getpid(), signal.SIGTERM)
            for f in futs:
                f.result(timeout=120)
            assert wait_until(lambda: router.state == STOPPED, 30)
        finally:
            router.stop()
            for p, _ in procs.values():
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            for p, _ in procs.values():
                try:
                    p.wait(timeout=20)
                except Exception:  # noqa: BLE001 — wedged child
                    p.kill()
    _, events = obs_events.replay_events(log_path)
    sec = run_report.build_router_section(events)
    # the outcome-total identity over the WHOLE chain, zero unresolved
    assert sec["outcomes"]["unresolved"] == 0, sec["lost_requests"]
    assert sec["outcomes"]["admitted"] == sec["outcomes"]["terminals"]
    assert sec["outcomes"]["results"] >= 80
    assert sec["outcomes"]["deadline_exceeded"] >= 1
    assert sec["outcomes"]["shed_admitted"] >= 1
    # the failover is in the log: off-budget reroutes away from the victim
    reroutes = [e for e in events if e.get("event") == "retry"
                and e.get("scope") == "router"
                and e.get("via") == "reroute"]
    assert reroutes and all(e["on_budget"] is False for e in reroutes)
    # the victim's death AND probe-driven resurrection are in the timeline
    vid = [b for b, row in sec["backends"].items() if row["deaths"] >= 1]
    assert len(vid) == 1
    assert sec["backends"][vid[0]]["resurrections"] >= 1
    # router-level lifecycle: READY → DEGRADED → READY → DRAINING → STOPPED
    rt_states = [e["state"] for e in events
                 if e.get("event") == "route_health"]
    assert rt_states == [READY, DEGRADED, READY, "DRAINING", STOPPED]
    drains = [e for e in events if e.get("event") == "route_drain"]
    assert len(drains) == 1 and drains[0]["drained"] is True \
        and drains[0]["leftover"] == 0
    assert run_report.main([log_path, "--serving"]) == 0


# ---------------------------------------------------------------------------
# tools: the pod-tier probe smoke (tier-1)
# ---------------------------------------------------------------------------


def test_serve_probe_router_tiny_smoke(capsys):
    rc = serve_probe.main(["--router", "2", "--tiny", "--pairs", "4",
                           "--burst-factor", "1.0"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)["router"]
    assert doc["backends"] == 2
    assert doc["capacity_qps"] > 0
    assert doc["latency_ms"]["n"] == 4
    # the SIGKILL failover sweep ran and lost nothing
    assert doc["failover"]["lost"] == 0
    assert doc["failover"]["pause_ms"] >= 0
    assert "shed_pct" in doc["burst"]
    assert doc["health"]["role"] == "router"
