"""End-to-end test of the real-weights parity kit (tools/parity_kit.py)
against a synthetically written reference-format ``.pth.tar`` — so the kit is
proven runnable the day the released checkpoint and dataset are reachable
(VERDICT r2 "Missing #2")."""

import argparse
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
sys.path.insert(0, os.path.dirname(__file__))

from test_backbone import make_resnet101_state_dict  # noqa: E402

import parity_kit  # noqa: E402


@pytest.fixture(scope="module")
def torch_ckpt(tmp_path_factory):
    """Reference-format .pth.tar on disk: Sequential-indexed resnet101 trunk
    + one pre-permuted Conv4d layer + the argparse args the reference stores
    (lib/model.py:211-220)."""
    import torch

    rng = np.random.default_rng(0)
    name_to_idx = {"conv1": "0", "bn1": "1", "layer1": "4", "layer2": "5",
                   "layer3": "6"}
    sd = {}
    for k, v in make_resnet101_state_dict().items():
        name, _, tail = k.partition(".")
        sd[f"FeatureExtraction.model.{name_to_idx[name]}.{tail}"] = torch.tensor(v)
    w = rng.standard_normal((3, 3, 3, 3, 1, 1)).astype(np.float32) * 0.2
    sd["NeighConsensus.conv.0.weight"] = torch.tensor(
        np.transpose(w, (0, 5, 4, 1, 2, 3))
    )
    sd["NeighConsensus.conv.0.bias"] = torch.tensor(np.zeros(1, np.float32))
    path = tmp_path_factory.mktemp("ckpt") / "synthetic_ncnet.pth.tar"
    torch.save(
        {
            "state_dict": sd,
            "args": argparse.Namespace(
                ncons_kernel_sizes=[3], ncons_channels=[1],
                feature_extraction_cnn="resnet101",
            ),
        },
        str(path),
    )
    return str(path)


@pytest.fixture(scope="module")
def pf_root(tmp_path_factory):
    from ncnet_tpu.data.synthetic import write_pf_pascal_like

    root = str(tmp_path_factory.mktemp("pf"))
    write_pf_pascal_like(root, n_pairs=3, image_hw=(96, 96), shift=(16, 16))
    return root


def test_pck_command(torch_ckpt, pf_root, capsys):
    rc = parity_kit.main([
        "--torch_checkpoint", torch_ckpt, "--dataset", pf_root,
        "--image_size", "64", "--quiet",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "PCK:" in out and "3/3 valid" in out


def test_trace_and_compare(torch_ckpt, pf_root, tmp_path, capsys):
    ours = str(tmp_path / "ours.npz")
    rc = parity_kit.main([
        "--torch_checkpoint", torch_ckpt, "--dataset", pf_root,
        "--image_size", "64", "--record_trace", ours, "--pairs", "2",
    ])
    assert rc == 0
    data = np.load(ours)
    for stage in ("feature_A", "feature_B", "corr_raw", "corr_filtered",
                  "matches"):
        assert f"{stage}_0" in data.files and f"{stage}_1" in data.files
    assert data["corr_raw_0"].ndim == 5
    assert data["matches_0"].shape[0] == 5

    # identical traces pass
    assert parity_kit.main(["--compare", ours, ours]) == 0
    capsys.readouterr()

    # a perturbed stage fails the tolerance and is named in the report
    theirs = str(tmp_path / "theirs.npz")
    arrays = {k: data[k].copy() for k in data.files}
    arrays["corr_filtered_1"] = arrays["corr_filtered_1"] + 1.0
    np.savez_compressed(theirs, **arrays)
    assert parity_kit.main(["--compare", ours, theirs, "--tolerance", "0.1"]) == 1
    assert "corr_filtered_1" in capsys.readouterr().out

    # a truncated trace must FAIL (not silently pass on the intersection)
    trunc = str(tmp_path / "trunc.npz")
    np.savez_compressed(
        trunc, **{k: data[k] for k in data.files if k.startswith("feature")}
    )
    assert parity_kit.main(["--compare", ours, trunc]) == 1
    assert parity_kit.main(["--compare", ours, trunc, "--allow_missing"]) == 0


def test_all_runbook(torch_ckpt, pf_root, capsys):
    """The --all real-weights-day runbook end-to-end on the synthetic
    checkpoint: import + arch report + torch-twin activation golden-check +
    PCK vs the ⚠ 78.9% target — proving the single command runs before the
    day the released weights are reachable (VERDICT r4 item 6)."""
    rc = parity_kit.main([
        "--all", "--pfpascal_checkpoint", torch_ckpt, "--ivd_checkpoint", "",
        "--dataset", pf_root, "--image_size", "64", "--quiet",
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "twin activation check" in out and "PASS" in out
    assert "PCK@0.1" in out and "78.9%" in out
    assert "arch: backbone=resnet101" in out
    assert "[ivd] no checkpoint given" in out

    # --expect_pck converts a shortfall into exit 1 (synthetic identity-less
    # weights cannot hit 101%)
    rc = parity_kit.main([
        "--all", "--pfpascal_checkpoint", torch_ckpt, "--ivd_checkpoint", "",
        "--dataset", pf_root, "--image_size", "64", "--quiet",
        "--expect_pck", "101.0",
    ])
    assert rc == 1

    # --expect_pck with no way to run PCK must FAIL, not silently pass
    rc = parity_kit.main([
        "--all", "--pfpascal_checkpoint", torch_ckpt, "--ivd_checkpoint", "",
        "--image_size", "64", "--quiet", "--expect_pck", "50.0",
    ])
    assert rc == 1
    assert "never ran" in capsys.readouterr().out

    # an EXPLICIT missing checkpoint path is a typo → argparse error, not a
    # silent skip
    with pytest.raises(SystemExit):
        parity_kit.main([
            "--all", "--pfpascal_checkpoint", "/nonexistent/x.pth.tar",
        ])


def test_legacy_vgg_rekey_checkpoint(tmp_path, capsys):
    """The reference's oldest checkpoints key the trunk as
    'FeatureExtraction.vgg.*'; load-time it renames 'vgg'→'model'
    (/root/reference/lib/model.py:225-232).  A fabricated legacy checkpoint
    must load through NCNet(checkpoint=...) end-to-end and produce the SAME
    params and forward as its modern-keyed twin (VERDICT r4 item 6)."""
    import argparse as ap

    import torch

    import jax.numpy as jnp

    from ncnet_tpu.config import ModelConfig
    from ncnet_tpu.models import NCNet

    rng = np.random.default_rng(3)
    name_to_idx = {"conv1": "0", "bn1": "1", "layer1": "4", "layer2": "5",
                   "layer3": "6"}
    modern, legacy = {}, {}
    for k, v in make_resnet101_state_dict().items():
        name, _, tail = k.partition(".")
        t = torch.tensor(v)
        modern[f"FeatureExtraction.model.{name_to_idx[name]}.{tail}"] = t
        legacy[f"FeatureExtraction.vgg.{name_to_idx[name]}.{tail}"] = t
    w = rng.standard_normal((3, 3, 3, 3, 1, 1)).astype(np.float32) * 0.2
    for sd in (modern, legacy):
        sd["NeighConsensus.conv.0.weight"] = torch.tensor(
            np.transpose(w, (0, 5, 4, 1, 2, 3)))
        sd["NeighConsensus.conv.0.bias"] = torch.tensor(
            np.zeros(1, np.float32))
    args = ap.Namespace(ncons_kernel_sizes=[3], ncons_channels=[1],
                        feature_extraction_cnn="resnet101")
    p_modern = str(tmp_path / "modern.pth.tar")
    p_legacy = str(tmp_path / "legacy.pth.tar")
    torch.save({"state_dict": modern, "args": args}, p_modern)
    torch.save({"state_dict": legacy, "args": args}, p_legacy)

    import jax

    net_m = NCNet(ModelConfig(checkpoint=p_modern))
    net_l = NCNet(ModelConfig(checkpoint=p_legacy))
    leaves_m = [np.asarray(x) for x in jax.tree.leaves(net_m.params)]
    leaves_l = [np.asarray(x) for x in jax.tree.leaves(net_l.params)]
    assert len(leaves_m) == len(leaves_l)
    for a, b in zip(leaves_m, leaves_l):
        np.testing.assert_array_equal(a, b)

    x = rng.standard_normal((1, 48, 48, 3)).astype(np.float32)
    y = rng.standard_normal((1, 48, 48, 3)).astype(np.float32)
    out_m = np.asarray(net_m(jnp.asarray(x), jnp.asarray(y)).corr)
    out_l = np.asarray(net_l(jnp.asarray(x), jnp.asarray(y)).corr)
    np.testing.assert_array_equal(out_m, out_l)
    assert np.isfinite(out_l).all()
