"""End-to-end test of the real-weights parity kit (tools/parity_kit.py)
against a synthetically written reference-format ``.pth.tar`` — so the kit is
proven runnable the day the released checkpoint and dataset are reachable
(VERDICT r2 "Missing #2")."""

import argparse
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
sys.path.insert(0, os.path.dirname(__file__))

from test_backbone import make_resnet101_state_dict  # noqa: E402

import parity_kit  # noqa: E402


@pytest.fixture(scope="module")
def torch_ckpt(tmp_path_factory):
    """Reference-format .pth.tar on disk: Sequential-indexed resnet101 trunk
    + one pre-permuted Conv4d layer + the argparse args the reference stores
    (lib/model.py:211-220)."""
    import torch

    rng = np.random.default_rng(0)
    name_to_idx = {"conv1": "0", "bn1": "1", "layer1": "4", "layer2": "5",
                   "layer3": "6"}
    sd = {}
    for k, v in make_resnet101_state_dict().items():
        name, _, tail = k.partition(".")
        sd[f"FeatureExtraction.model.{name_to_idx[name]}.{tail}"] = torch.tensor(v)
    w = rng.standard_normal((3, 3, 3, 3, 1, 1)).astype(np.float32) * 0.2
    sd["NeighConsensus.conv.0.weight"] = torch.tensor(
        np.transpose(w, (0, 5, 4, 1, 2, 3))
    )
    sd["NeighConsensus.conv.0.bias"] = torch.tensor(np.zeros(1, np.float32))
    path = tmp_path_factory.mktemp("ckpt") / "synthetic_ncnet.pth.tar"
    torch.save(
        {
            "state_dict": sd,
            "args": argparse.Namespace(
                ncons_kernel_sizes=[3], ncons_channels=[1],
                feature_extraction_cnn="resnet101",
            ),
        },
        str(path),
    )
    return str(path)


@pytest.fixture(scope="module")
def pf_root(tmp_path_factory):
    from ncnet_tpu.data.synthetic import write_pf_pascal_like

    root = str(tmp_path_factory.mktemp("pf"))
    write_pf_pascal_like(root, n_pairs=3, image_hw=(96, 96), shift=(16, 16))
    return root


def test_pck_command(torch_ckpt, pf_root, capsys):
    rc = parity_kit.main([
        "--torch_checkpoint", torch_ckpt, "--dataset", pf_root,
        "--image_size", "64", "--quiet",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "PCK:" in out and "3/3 valid" in out


def test_trace_and_compare(torch_ckpt, pf_root, tmp_path, capsys):
    ours = str(tmp_path / "ours.npz")
    rc = parity_kit.main([
        "--torch_checkpoint", torch_ckpt, "--dataset", pf_root,
        "--image_size", "64", "--record_trace", ours, "--pairs", "2",
    ])
    assert rc == 0
    data = np.load(ours)
    for stage in ("feature_A", "feature_B", "corr_raw", "corr_filtered",
                  "matches"):
        assert f"{stage}_0" in data.files and f"{stage}_1" in data.files
    assert data["corr_raw_0"].ndim == 5
    assert data["matches_0"].shape[0] == 5

    # identical traces pass
    assert parity_kit.main(["--compare", ours, ours]) == 0
    capsys.readouterr()

    # a perturbed stage fails the tolerance and is named in the report
    theirs = str(tmp_path / "theirs.npz")
    arrays = {k: data[k].copy() for k in data.files}
    arrays["corr_filtered_1"] = arrays["corr_filtered_1"] + 1.0
    np.savez_compressed(theirs, **arrays)
    assert parity_kit.main(["--compare", ours, theirs, "--tolerance", "0.1"]) == 1
    assert "corr_filtered_1" in capsys.readouterr().out

    # a truncated trace must FAIL (not silently pass on the intersection)
    trunc = str(tmp_path / "trunc.npz")
    np.savez_compressed(
        trunc, **{k: data[k] for k in data.files if k.startswith("feature")}
    )
    assert parity_kit.main(["--compare", ours, trunc]) == 1
    assert parity_kit.main(["--compare", ours, trunc, "--allow_missing"]) == 0
