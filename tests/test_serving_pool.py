"""Chaos suite for the replicated serving pool (ncnet_tpu/serving/replica.py).

The ISSUE 10 acceptance bars, executed deterministically through the
utils/faults.py replica hooks (``dead_replica_ids`` / ``slow_replica_ids``)
against a 4-replica CPU pool:

  (a) sustained stream → SIGKILL-style death of one replica mid-batch →
      the service stays READY/DEGRADED with ZERO lost requests (the
      outcome-total identity recomputed from the event log names every
      request) → the replica resurrects via a probe and resumes taking
      traffic → SIGTERM drains the whole pool cleanly;
  (b) all-replicas-dead → DEGRADED with classified ``no_capacity``
      shedding (retry hints at the resurrection-probe period), admitted
      work PARKED off-budget, then full recovery on resurrection;
  (c) a slow replica's inflated batch walls make the health-scored router
      measurably de-prioritize it;
  (d) pool membership changes flow into admission control: queue bounds
      and retry-after hints track ready/total capacity elastically;
  (e) a REAL multi-device pool (``--xla_force_host_platform_device_count``)
      builds one engine per device and serves across all of them.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from ncnet_tpu import ops
from ncnet_tpu.observability import EventLog, Heartbeat
from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.serving import (
    DEGRADED,
    READY,
    REPLICA_DEAD,
    REPLICA_READY,
    STOPPED,
    AdmissionController,
    BatchMatchEngine,
    MatchService,
    Overloaded,
    Replica,
    ReplicaPool,
    ServingConfig,
)
from ncnet_tpu.utils import faults
from ncnet_tpu.utils.faults import FaultPlan

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import run_report  # noqa: E402
import stall_watchdog  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_state():
    """No armed faults, no demoted tiers, no leaked event sink."""
    faults.clear()
    ops.reset_fused_tier_demotions()
    obs_events.set_global_sink(None)
    yield
    faults.clear()
    ops.reset_fused_tier_demotions()
    obs_events.set_global_sink(None)


def u8(side=32, seed=0):
    return np.random.default_rng(seed).integers(
        0, 255, (side, side, 3), dtype=np.uint8)


class FakeEngine:
    """Device stand-in (same protocol as tests/test_serving.py): the
    replica-level chaos seams live in serving/replica.py's wrappers, so a
    fake engine behind a real Replica exercises the REAL failover paths."""

    split = staticmethod(BatchMatchEngine.split)
    half_precision = False

    def __init__(self, latency_s: float = 0.0):
        self.latency_s = latency_s
        self.retraces = 0
        self.dispatches = 0

    def dispatch(self, src, tgt):
        faults.device_error_hook("fake_serve")
        self.dispatches += 1
        return (src.shape[0], time.monotonic())

    def fetch(self, handle):
        b, t0 = handle
        while time.monotonic() - t0 < self.latency_s:
            time.sleep(0.01)
        table = np.zeros((b, 6, 16), np.float32)
        table[:, 4, :] = 1.0
        table[:, 5, :5] = [0.5, 0.1, 0.4, 0.9, 0.8]
        return table

    def retrace(self):
        self.retraces += 1


def pool_service(n=4, latency_s=0.02, **over):
    cfg = dict(bucket_multiple=32, max_image_side=64, max_batch=2,
               replica_max_failures=1, resurrect_after_s=0.2,
               # the chaos streams saturate from ONE client; the fairness
               # cap must exceed the stream depth or the tests shed
               # themselves
               max_queue=128, max_in_flight_per_client=128)
    cfg.update(over)
    engines = [FakeEngine(latency_s=latency_s) for _ in range(n)]
    return MatchService(engine=engines,
                        serving=ServingConfig(**cfg)), engines


def wait_until(pred, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# ---------------------------------------------------------------------------
# units: pool routing, health scores, elastic admission
# ---------------------------------------------------------------------------


def test_replica_health_score_and_routing():
    a, b, c = (Replica("a", object()), Replica("b", object()),
               Replica("c", object()))
    pool = ReplicaPool([a, b, c])
    a.note_success(0.01)
    b.note_success(0.2)
    # measured-fastest wins; an unmeasured replica routes at the prior
    assert pool.route(max_load=2).id == "a"
    a.pending.extend([object(), object()])  # a at full depth
    assert pool.route(max_load=2).id == "c"  # prior 0.05 beats b's 0.2
    # a failure streak doubles the score per consecutive failure
    c.note_failure()
    c.note_failure()
    assert c.health_score() == pytest.approx(0.05 * 4)
    assert pool.route(max_load=2).id == "b"
    c.note_success(0.01)  # a success clears the streak
    assert c.consecutive_failures == 0
    # exclusion prefers fresh replicas but falls back rather than strand
    assert pool.route(max_load=2, exclude=frozenset({"c"})).id == "b"
    assert pool.route(
        max_load=2, exclude=frozenset({"b", "c"})).id in ("b", "c")
    pool.mark_dead(b, "test")
    pool.mark_dead(c, "test")
    assert b.state == REPLICA_DEAD and b.deaths == 1
    assert pool.route(max_load=2) is None  # a full, b/c dead
    pool.resurrect(c)
    assert c.state == REPLICA_READY and c.ewma_wall_s is None
    assert pool.route(max_load=2).id == "c"


def test_pool_due_probes_are_periodic_and_skip_loaded():
    a, b = Replica("a", object()), Replica("b", object())
    pool = ReplicaPool([a, b])
    pool.mark_dead(a, "test")
    pool.mark_dead(b, "test")
    b.pending.append(object())  # still draining its backlog: not probeable
    t0 = a.dead_since
    assert pool.due_probes(t0 + 0.05, 0.2) == []
    due = pool.due_probes(t0 + 0.25, 0.2)
    assert due == [a]  # b skipped while loaded
    # the probing flag was stamped: while the probe thread is out the
    # replica is never double-scheduled, no matter how late it runs
    assert a.probing is True
    assert pool.due_probes(t0 + 10.0, 0.2) == []
    a.probing = False  # the probe returned (and failed)
    # last_probe_t was stamped too: not due again until another period
    assert pool.due_probes(t0 + 0.3, 0.2) == []
    assert pool.due_probes(t0 + 0.5, 0.2) == [a]


def test_admission_tracks_pool_capacity_elastically():
    """Satellite: retry_after_s derives from AGGREGATE pool cadence
    (batch wall / ready replicas) and the queue bound scales with the live
    ready fraction; an all-dead pool sheds classified no_capacity with the
    resurrection period as the hint."""
    a = AdmissionController(max_queue=64, max_in_flight_per_client=64,
                            max_batch=8, dead_retry_after_s=2.5)
    a.note_batch_wall(0.4)
    a.note_capacity(4, 4)
    assert a.retry_after_s(8) == pytest.approx(0.1, rel=0.01)
    a.note_capacity(1, 4)  # three replicas died: hints stretch 4x
    assert a.retry_after_s(8) == pytest.approx(0.4, rel=0.01)
    assert a.effective_max_queue() == 16
    a.note_capacity(3, 4)
    assert a.effective_max_queue() == 48
    a.note_capacity(0, 4)
    with pytest.raises(Overloaded) as e:
        a.admit("c", 0)
    assert e.value.reason == "no_capacity"
    assert e.value.retry_after_s == pytest.approx(2.5)
    # the bound floors at one batch so a lone survivor still coalesces
    b = AdmissionController(max_queue=16, max_in_flight_per_client=8,
                            max_batch=8)
    b.note_capacity(1, 8)
    assert b.effective_max_queue() == 8
    # elastic off: the static PR 8 bound regardless of membership
    c = AdmissionController(max_queue=64, max_in_flight_per_client=8,
                            max_batch=8, elastic=False)
    c.note_capacity(1, 4)
    assert c.effective_max_queue() == 64


# ---------------------------------------------------------------------------
# routing behavior under load
# ---------------------------------------------------------------------------


def test_pool_spreads_load_and_tags_events(tmp_path):
    """Every replica takes traffic under a sustained stream, and the
    serve_batch / serve_result / quality events are replica-tagged."""
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc, engines = pool_service(n=3, latency_s=0.03, max_batch=1)
        svc.start()
        img = u8()
        futs = [svc.submit(img, img) for _ in range(18)]
        for f in futs:
            f.result(timeout=60)
        svc.stop()
    _, events = obs_events.replay_events(log_path)
    batch_reps = {e["replica"] for e in events
                  if e.get("event") == "serve_batch"}
    assert batch_reps == {"rep0", "rep1", "rep2"}
    result_reps = {e.get("replica") for e in events
                   if e.get("event") == "serve_result"}
    assert result_reps <= batch_reps and result_reps
    quality_reps = {e.get("replica") for e in events
                    if e.get("event") == "quality"}
    assert quality_reps and None not in quality_reps
    sec = run_report.build_serving_section(events)
    assert set(sec["replicas"]) == {"rep0", "rep1", "rep2"}
    assert sum(r["batches"] for r in sec["replicas"].values()) == 18
    assert sec["outcomes"]["unresolved"] == 0


def test_slow_replica_is_deprioritized():
    """Acceptance (c): an injected slow replica (its fetches sleep) ends up
    with an inflated wall EWMA and a worse health score, and the router
    sends it measurably less traffic than its healthy peer."""
    svc, engines = pool_service(n=2, latency_s=0.01, max_batch=1,
                                replica_max_failures=5)
    faults.install(FaultPlan(slow_replica_ids=("rep1",),
                             slow_replica_seconds=0.25))
    try:
        svc.start()
        img = u8()
        futs = [svc.submit(img, img) for _ in range(24)]
        for f in futs:
            f.result(timeout=60)
        health = svc.health()
    finally:
        faults.clear()
        svc.stop()
    rep = {r["id"]: r for r in health["pool"]["replicas"]}
    assert rep["rep0"]["batches"] + rep["rep1"]["batches"] == 24
    assert rep["rep1"]["batches"] >= 1  # it did serve — just rarely
    assert rep["rep0"]["batches"] >= 3 * rep["rep1"]["batches"]
    # the telemetry that fed the decision: slow EWMA, worse score
    assert rep["rep1"]["ewma_wall_ms"] > rep["rep0"]["ewma_wall_ms"] * 5
    assert rep["rep1"]["score"] > rep["rep0"]["score"]


# ---------------------------------------------------------------------------
# chaos: replica death, failover, resurrection, all-dead
# ---------------------------------------------------------------------------


def test_acceptance_chain_kill_resurrect_drain(tmp_path):
    """THE ISSUE 10 acceptance chain on a 4-replica pool: sustained stream
    → rep2 dies mid-batch (dispatch succeeded, fetch raises — the
    SIGKILL-style chip death) → zero lost requests, service DEGRADED but
    serving → faults heal, the resurrection probe returns rep2 to READY
    and the pool to full strength (service back to READY — no tier was
    demoted, so the capacity DEGRADED recovers) → rep2 takes traffic again
    → SIGTERM drains the whole pool cleanly."""
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc, engines = pool_service(n=4, install_sigterm=True)
        svc.start()
        img = u8()
        # phase 1: healthy sustained stream
        for f in [svc.submit(img, img) for _ in range(8)]:
            f.result(timeout=60)
        assert svc.state == READY
        # phase 2: rep2 dies mid-batch; every request still resolves
        faults.install(FaultPlan(dead_replica_ids=("rep2",)))
        futs = [svc.submit(img, img) for _ in range(16)]
        for f in futs:
            f.result(timeout=60)
        assert all(f.outcome == "result" for f in futs)
        assert wait_until(lambda: svc.health()["pool"]["ready"] == 3)
        h = svc.health()
        assert h["state"] == DEGRADED
        assert {r["id"]: r["state"] for r in h["pool"]["replicas"]}["rep2"] \
            == REPLICA_DEAD
        # elastic admission: the advertised queue shrank with the pool
        assert h["queue"]["effective_max_queue"] < svc.cfg.max_queue
        # probes fire while the fault is armed — and fail
        assert wait_until(lambda: any(
            r["probes"] for r in
            run_report.build_serving_section(
                obs_events.replay_events(log_path)[1])["replicas"].values()
        ), timeout=5.0, interval=0.1)
        # phase 3: heal the chip; the probe resurrects rep2
        faults.clear()
        assert wait_until(lambda: svc.health()["pool"]["ready"] == 4)
        assert svc.state == READY  # capacity DEGRADED recovered, no tier down
        # phase 4: rep2 takes traffic again
        futs = [svc.submit(img, img) for _ in range(16)]
        for f in futs:
            f.result(timeout=60)
        # phase 5: SIGTERM drains the whole pool cleanly
        os.kill(os.getpid(), signal.SIGTERM)
        assert wait_until(lambda: svc.state == STOPPED)
        svc.stop()  # restores the handler; worker already gone
    _, events = obs_events.replay_events(log_path)
    sec = run_report.build_serving_section(events)
    # the outcome-total identity over the whole chain: 40 admitted, 40
    # results, nothing lost, nothing quarantined
    assert sec["outcomes"]["admitted"] == 40
    assert sec["outcomes"]["results"] == 40
    assert sec["outcomes"]["unresolved"] == 0 and not sec["lost_requests"]
    assert sec["outcomes"]["quarantined"] == 0
    # the failover is in the log: off-budget reroutes away from rep2
    reroutes = [e for e in events if e.get("event") == "retry"
                and e.get("via") == "reroute"]
    assert reroutes and all(e["replica"] == "rep2" for e in reroutes)
    assert all(e["on_budget"] is False for e in reroutes)
    # no tier demotion happened: failover, not program change
    assert not ops.demoted_fused_tiers()
    assert all(e.retraces == 0 for e in engines)
    # replica lifecycle in the health timeline: rep2 DEAD then READY
    rep2 = sec["replicas"]["rep2"]
    assert rep2["deaths"] == 1 and rep2["resurrections"] == 1
    assert rep2["batches"] >= 1  # it resumed taking traffic after probe_ok
    states = [(e.get("replica"), e["state"]) for e in events
              if e.get("event") == "serve_health"]
    assert ("rep2", REPLICA_DEAD) in states
    assert states.index(("rep2", REPLICA_READY)) \
        > states.index(("rep2", REPLICA_DEAD))
    # rep2 served real batches AFTER its resurrection
    resurrect_seq = next(
        i for i, e in enumerate(events) if e.get("event") == "serve_health"
        and e.get("replica") == "rep2" and e["state"] == REPLICA_READY)
    assert any(e.get("event") == "serve_batch" and e.get("replica") == "rep2"
               for e in events[resurrect_seq:])
    # service-level timeline: READY -> DEGRADED -> READY -> DRAINING -> STOPPED
    svc_states = [e["state"] for e in events
                  if e.get("event") == "serve_health"
                  and e.get("replica") is None]
    assert svc_states == [READY, DEGRADED, READY, "DRAINING", STOPPED]
    drains = [e for e in events if e.get("event") == "serve_drain"]
    assert len(drains) == 1 and drains[0]["drained"] is True \
        and drains[0]["leftover"] == 0
    # the tool renders the pool postmortem end to end
    assert run_report.main([log_path, "--serving"]) == 0


def test_all_replicas_dead_sheds_then_recovers(tmp_path):
    """Acceptance (b): every replica dead → admitted work PARKS off-budget
    (zero lost), new admissions shed classified ``no_capacity`` with the
    resurrection period as the retry hint, service DEGRADED — then the
    probes revive the pool, the parked work completes, and full membership
    restores READY."""
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc, engines = pool_service(n=2, max_batch=1, resurrect_after_s=0.25)
        svc.start()
        img = u8()
        faults.install(FaultPlan(dead_replica_ids=("rep0", "rep1")))
        f1 = svc.submit(img, img)
        f_dl = svc.submit(img, img, deadline_s=0.3)
        assert wait_until(lambda: svc.health()["pool"]["ready"] == 0)
        assert svc.state == DEGRADED
        assert f1.outcome is None  # parked behind the probes, not lost
        with pytest.raises(Overloaded) as e:
            svc.submit(img, img)
        assert e.value.reason == "no_capacity"
        assert e.value.retry_after_s == pytest.approx(0.25)
        # a parked request whose budget expires is still evicted with the
        # classified deadline outcome — even with NOTHING routable
        from ncnet_tpu.serving import DeadlineExceeded

        with pytest.raises(DeadlineExceeded) as de:
            f_dl.result(timeout=10)
        assert de.value.where == "dequeue"
        # heal: the next probe resurrects a replica and the stream resumes
        faults.clear()
        assert f1.result(timeout=60).request_id == f1.request_id
        assert f1.outcome == "result"
        assert wait_until(lambda: svc.health()["pool"]["ready"] == 2)
        assert svc.state == READY
        svc.stop()
    _, events = obs_events.replay_events(log_path)
    parked = [e for e in events if e.get("event") == "retry"
              and e.get("via") == "awaiting_capacity"]
    assert parked and all(e["on_budget"] is False for e in parked)
    sheds = [e for e in events if e.get("event") == "serve_shed"]
    assert any(e.get("reason") == "no_capacity" for e in sheds)
    sec = run_report.build_serving_section(events)
    assert sec["outcomes"]["unresolved"] == 0
    assert sec["outcomes"]["results"] == 1
    deaths = sum(r["deaths"] for r in sec["replicas"].values())
    assert deaths == 2


def test_single_replica_pool_keeps_pr8_tier_recovery(tmp_path):
    """A pool of one has no survivor to fail over to: a device-shaped
    failure must still walk the PR 8 demote-retrace ladder (free retry on a
    program change) — the replica is NOT killed for a failure the tier
    recovery absorbed."""
    svc, engines = pool_service(n=1, max_batch=1, replica_max_failures=3)
    svc.start()
    faults.install(FaultPlan(device_fail_calls=(1,)))
    try:
        f = svc.submit(u8(), u8())
        assert f.result(timeout=60).request_id
        assert f.outcome == "result"
    finally:
        faults.clear()
        svc.stop()
    assert engines[0].retraces == 1  # the recovery really retraced
    assert ops.demoted_fused_tiers()
    rep = svc.health()["pool"]["replicas"][0]
    assert rep["deaths"] == 0
    # the demotion its failure forced feeds the routing penalty + probe
    assert rep["demotions"] == 1


# ---------------------------------------------------------------------------
# liveness: one wedged replica must not flag a healthy pool (satellite)
# ---------------------------------------------------------------------------


def test_wedged_replica_does_not_stall_healthy_pool(tmp_path):
    """One replica wedges (its fetch hangs); survivors keep dispatching, so
    the pool-wide heartbeat stays fresh and the watchdog stays green —
    while the wedged lane is visibly 'not recent' in the breakdown."""
    hb = str(tmp_path / "heartbeat.json")
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc, engines = pool_service(n=2, latency_s=0.02, max_batch=1,
                                    heartbeat_path=hb,
                                    replica_max_failures=100)
        svc.start()
        img = u8()
        for f in [svc.submit(img, img) for _ in range(8)]:
            f.result(timeout=60)
        # wedge rep1's engine: its next fetch blocks ~forever.  Everything
        # not already stranded behind the wedged lane (it holds at most
        # pipeline-depth batches — a silent wedge is invisible at dispatch
        # time; fetch_timeout_s is the knob that converts it into a
        # failover) keeps resolving through rep0.
        engines[1].latency_s = 60.0
        futs = [svc.submit(img, img) for _ in range(12)]
        assert wait_until(lambda: sum(
            f.outcome is not None for f in futs) >= 8, timeout=20)
        # a second wave under the standing wedge: still served (by rep0)
        futs2 = [svc.submit(img, img) for _ in range(4)]
        assert wait_until(lambda: all(
            f.outcome is not None for f in futs2), timeout=20)
        # judged immediately: the pool-wide heartbeat and rep0's cadence
        # are fresh, so one wedged replica does NOT flag the pool
        v = stall_watchdog.judge(hb, events_path=log_path, factor=5,
                                 min_age=0.5)
        assert v["status"] == "alive"
        assert v["replicas"]["rep0"]["recent"] is True
        # release the wedge so shutdown is clean
        engines[1].latency_s = 0.0
        for f in futs:
            f.result(timeout=60)
        svc.stop(timeout=60)


def test_stall_watchdog_alive_via_replica_cadence(tmp_path):
    """The event-log backstop: a stale heartbeat (file unwritable, clock
    skew) must not flag a pool whose log shows a lane still draining — and
    with every lane stale the verdict is honestly STALLED."""
    hb = str(tmp_path / "heartbeat.json")
    log_path = str(tmp_path / "events.jsonl")
    now = time.time()
    with obs_events.bound(EventLog(log_path)):
        for _ in range(4):  # a long-wedged lane...
            obs_events.emit("serve_batch", replica="rep0", wall_s=0.05,
                            t=now - 60)
        for _ in range(4):  # ...and a lane that drained moments ago
            obs_events.emit("serve_batch", replica="rep1", wall_s=0.05,
                            t=now - 0.5)
    Heartbeat(hb).beat(step=1)
    os.utime(hb, (now - 60, now - 60))  # heartbeat looks long dead
    v = stall_watchdog.judge(hb, events_path=log_path, factor=5, min_age=2.0)
    assert v["status"] == "alive"
    assert v["alive_via"] == "replica_cadence:rep1"
    assert v["replicas"]["rep0"]["recent"] is False
    assert v["replicas"]["rep1"]["recent"] is True
    # every lane stale: genuinely stalled, named per replica
    log2 = str(tmp_path / "events2.jsonl")
    with obs_events.bound(EventLog(log2)):
        for rid in ("rep0", "rep1"):
            obs_events.emit("serve_batch", replica=rid, wall_s=0.05,
                            t=now - 60)
    v = stall_watchdog.judge(hb, events_path=log2, factor=5, min_age=2.0)
    assert v["status"] == "stalled"
    assert not any(r["recent"] for r in v["replicas"].values())


# ---------------------------------------------------------------------------
# real engines: a multi-device pool end to end
# ---------------------------------------------------------------------------


_MULTIDEV_CHILD = """
import json, sys, warnings
import numpy as np

sys.path.insert(0, {repo!r})
import jax

from ncnet_tpu import models
from ncnet_tpu.config import ModelConfig
from ncnet_tpu.observability import EventLog
from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.serving import MatchService, ServingConfig

assert len(jax.devices()) == 4, jax.devices()
cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                  ncons_channels=(1,))
with warnings.catch_warnings():
    warnings.simplefilter("ignore")
    params = models.init_ncnet(cfg, jax.random.key(0))
obs_events.set_global_sink(EventLog(sys.argv[1]))
svc = MatchService(cfg, params, ServingConfig(
    bucket_multiple=32, max_image_side=32, max_batch=1,
    replicas=0)).start()  # 0 = one replica per visible device
rng = np.random.default_rng(0)
futs = [svc.submit(rng.integers(0, 255, (32, 32, 3), dtype=np.uint8),
                   rng.integers(0, 255, (32, 32, 3), dtype=np.uint8))
        for _ in range(8)]
tables = [f.result(timeout=300).table for f in futs]
health = svc.health()
svc.stop()
print(json.dumps({{
    "n_results": len(tables),
    "table_rows": int(tables[0].shape[0]),
    "replicas": [r["id"] for r in health["pool"]["replicas"]],
    "devices": sorted({{r["device"] for r in health["pool"]["replicas"]}}),
}}))
"""


def test_real_pool_one_engine_per_forced_host_device(tmp_path):
    """Acceptance (e): ``XLA_FLAGS=--xla_force_host_platform_device_count=4``
    gives the child four CPU devices; ``replicas=0`` builds one real
    BatchMatchEngine per device (params committed per device) and the
    stream is served across them."""
    log_path = str(tmp_path / "events.jsonl")
    child = tmp_path / "child.py"
    child.write_text(_MULTIDEV_CHILD.format(repo=_REPO))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=4",
               NCNET_TPU_PERF_STORE="off", NCNET_TPU_TIER_CACHE="off")
    proc = subprocess.run(
        [sys.executable, str(child), log_path],
        capture_output=True, text=True, env=env, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    doc = json.loads(proc.stdout.strip().splitlines()[-1])
    assert doc["n_results"] == 8 and doc["table_rows"] == 5
    assert doc["replicas"] == ["rep0", "rep1", "rep2", "rep3"]
    assert len(doc["devices"]) == 4  # four DISTINCT devices, one each
    _, events = obs_events.replay_events(log_path)
    batch_reps = {e["replica"] for e in events
                  if e.get("event") == "serve_batch"}
    # the router spread the stream across the pool (at minimum it used
    # more than one device; typically all four)
    assert len(batch_reps) >= 2
    sec = run_report.build_serving_section(events)
    assert sec["outcomes"]["admitted"] == 8
    assert sec["outcomes"]["unresolved"] == 0


# ---------------------------------------------------------------------------
# tools: probe sweep smoke, report rendering
# ---------------------------------------------------------------------------


def test_serve_probe_replica_sweep_tiny_smoke(tmp_path, capsys):
    import serve_probe

    rc = serve_probe.main(["--tiny", "--sides", "32", "--pairs", "4",
                           "--no-demote", "--burst-factor", "1.0",
                           "--replicas", "1,2"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert set(doc["replica_sweep"]) == {"r1", "r2"}
    for r in doc["replica_sweep"].values():
        assert r["qps"] > 0 and r["latency_ms"]["n"] == 4
    # a single-CPU test host oversubscribes r2 and says so
    assert doc["replica_sweep"]["r2"]["oversubscribed"] \
        == (doc["visible_devices"] < 2)


def test_run_report_renders_replica_section(tmp_path, capsys):
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc, _ = pool_service(n=2, latency_s=0.01, max_batch=1)
        svc.start()
        img = u8()
        for f in [svc.submit(img, img) for _ in range(6)]:
            f.result(timeout=60)
        svc.stop()
    assert run_report.main([log_path, "--serving"]) == 0
    out = capsys.readouterr().out
    assert "replicas:" in out
    assert "rep0:" in out and "rep1:" in out
    assert "exactly one terminal outcome" in out
