"""Chaos suite for the resident match service (ncnet_tpu/serving/).

The ISSUE 8 acceptance bars, executed deterministically through the
utils/faults.py harness:

  (a) sustained synthetic query stream → injected device failure mid-stream
      → the service demotes a tier and KEEPS SERVING with zero lost
      requests (every admitted request reaches exactly one terminal
      outcome, proven by event-log accounting in run_report --serving);
  (b) SIGTERM → in-flight requests complete, the drain event is emitted,
      admission stays closed, clean exit;
  (c) an overload burst sheds with classified ``Overloaded`` (and never
      deadline-blows admitted work), and deadline-expired requests are
      evicted before dispatch;
  (d) kill-mid-drain (SIGKILL) → the replayed event log still accounts for
      every admitted request, naming the ones that died without an outcome.
"""

import json
import os
import signal
import subprocess
import sys
import time
import warnings

import numpy as np
import pytest
import jax

from ncnet_tpu import models, ops
from ncnet_tpu.config import ModelConfig
from ncnet_tpu.observability import EventLog
from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.serving import (
    DEGRADED,
    DRAINING,
    READY,
    STARTING,
    STOPPED,
    AdmissionController,
    BatchMatchEngine,
    DeadlineExceeded,
    HealthMachine,
    MatchService,
    Overloaded,
    RequestQuarantined,
    ServingConfig,
    ShapeBucketer,
)
from ncnet_tpu.utils import faults
from ncnet_tpu.utils.faults import FaultPlan, queue_overflow_burst

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import run_report  # noqa: E402
import stall_watchdog  # noqa: E402

TINY = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                   ncons_channels=(1,))


@pytest.fixture(autouse=True)
def _clean_state():
    """No armed faults, no demoted tiers, no leaked event sink."""
    faults.clear()
    ops.reset_fused_tier_demotions()
    obs_events.set_global_sink(None)
    yield
    faults.clear()
    ops.reset_fused_tier_demotions()
    obs_events.set_global_sink(None)


@pytest.fixture(scope="module")
def tiny_params():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return models.init_ncnet(TINY, jax.random.key(0))


def u8(side=32, seed=0):
    return np.random.default_rng(seed).integers(
        0, 255, (side, side, 3), dtype=np.uint8)


class FakeEngine:
    """Device stand-in for lifecycle tests: deterministic tables, a
    configurable fetch latency, and the same fault-injection seams as the
    real engine (``device_error_hook`` at dispatch; the hang hook fires
    inside ``call_with_watchdog`` when a fetch timeout is configured)."""

    split = staticmethod(BatchMatchEngine.split)
    half_precision = False

    def __init__(self, latency_s: float = 0.0):
        self.latency_s = latency_s
        self.retraces = 0
        self.dispatches = 0
        self.batch_sizes = []  # PADDED sizes, as a jit cache would key them

    def dispatch(self, src, tgt):
        faults.device_error_hook("fake_serve")
        self.dispatches += 1
        self.batch_sizes.append(src.shape[0])
        return (src.shape[0], time.monotonic())

    def fetch(self, handle):
        b, t0 = handle
        # poll the latency knob so a test can release a simulated wedge
        # mid-fetch (lowering latency_s frees the blocked worker at once)
        while time.monotonic() - t0 < self.latency_s:
            time.sleep(0.01)
        table = np.zeros((b, 6, 16), np.float32)
        table[:, 4, :] = 1.0
        table[:, 5, :5] = [0.5, 0.1, 0.4, 0.9, 0.8]
        return table

    def retrace(self):
        self.retraces += 1


def fake_service(tmp_path=None, latency_s=0.0, **over):
    cfg = dict(bucket_multiple=32, max_image_side=128, max_batch=8)
    cfg.update(over)
    engine = FakeEngine(latency_s=latency_s)
    return MatchService(engine=engine, serving=ServingConfig(**cfg)), engine


# ---------------------------------------------------------------------------
# units: bucketer, admission, health
# ---------------------------------------------------------------------------


def test_bucketer_rounds_caps_and_bounds():
    b = ShapeBucketer(multiple=32, max_side=64, max_buckets=2)
    assert b.bucket_for((30, 33), (10, 10)) == ((32, 64), (32, 32))
    assert b.bucket_for((32, 64), (32, 32)) == ((32, 64), (32, 32))
    # too large for any bucket: unservable, retry can never help
    with pytest.raises(Overloaded) as e:
        b.bucket_for((100, 10), (10, 10))
    assert e.value.reason == "unservable_shape"
    # a THIRD distinct pair bucket exceeds the compiled-program budget
    b.bucket_for((64, 64), (64, 64))
    with pytest.raises(Overloaded) as e:
        b.bucket_for((10, 10), (10, 10))
    assert e.value.reason == "bucket_capacity"

    fixed = ShapeBucketer(fixed=[(48, 48), (96, 96)], max_buckets=4)
    assert fixed.bucket_for((40, 40), (50, 50)) == ((48, 48), (96, 96))
    with pytest.raises(Overloaded):
        fixed.bucket_for((97, 10), (10, 10))


def test_shed_request_does_not_consume_bucket_budget():
    """peek is budget-free; only an ADMITTED request commits a compiled-
    program slot — a burst of shed requests with novel shapes must not
    permanently burn the bucket budget."""
    b = ShapeBucketer(multiple=32, max_side=64, max_buckets=1)
    assert b.peek((10, 10), (10, 10)) == ((32, 32), (32, 32))
    assert b.buckets == []
    b.commit(((32, 32), (32, 32)))
    with pytest.raises(Overloaded):
        b.peek((64, 64), (64, 64))

    svc, eng = fake_service(latency_s=0.3, max_queue=1, max_batch=1,
                            pipeline_depth=1, max_buckets=2)
    svc.start()
    try:
        f1 = svc.submit(u8(32, 1), u8(32, 1))  # bucket A, goes in flight
        time.sleep(0.05)
        f2 = svc.submit(u8(32, 2), u8(32, 2))  # fills the 1-deep queue
        with pytest.raises(Overloaded) as e:
            svc.submit(u8(40, 3), u8(40, 3))   # NEW shape, queue full
        assert e.value.reason == "queue_full"
        assert svc.health()["queue"]["buckets"] == ["32x32-32x32"]  # no leaked slot
        f1.result(timeout=30)
        f2.result(timeout=30)
        # the previously-shed shape is admissible once there is room
        assert svc.submit(u8(40, 4), u8(40, 4)).result(timeout=30)
    finally:
        svc.stop()


def test_admission_controller_bounds_and_retry_after():
    a = AdmissionController(max_queue=2, max_in_flight_per_client=2,
                            max_batch=2)
    a.admit("c1", 0)
    a.note_admit("c1")
    a.admit("c1", 1)
    a.note_admit("c1")
    with pytest.raises(Overloaded) as e:
        a.admit("c2", 2)  # queue full
    assert e.value.reason == "queue_full" and e.value.retry_after_s > 0
    with pytest.raises(Overloaded) as e:
        a.admit("c1", 1)  # per-client cap
    assert e.value.reason == "client_cap"
    a.note_done("c1")
    a.admit("c1", 1)  # back under the cap
    # retry-after tracks measured throughput
    a.note_batch_wall(0.2)
    assert a.retry_after_s(8) == pytest.approx(4 * 0.2, rel=0.3)


def test_health_machine_transitions(tmp_path):
    with obs_events.bound(EventLog(str(tmp_path / "e.jsonl"))):
        h = HealthMachine()
        assert h.state == STARTING and h.admitting
        assert h.to(READY, "warm")
        assert not h.to(READY)  # idempotent re-entry is not an error
        assert h.to(DEGRADED, "tier_demoted:resident")
        assert h.admitting
        assert h.to(DRAINING, "sigterm") and not h.admitting
        with pytest.raises(RuntimeError):
            h.to(READY)
        assert h.to(STOPPED)
    _, events = obs_events.replay_events(str(tmp_path / "e.jsonl"))
    states = [e["state"] for e in events if e["event"] == "serve_health"]
    assert states == [READY, DEGRADED, DRAINING, STOPPED]
    assert h.probe()["state"] == STOPPED


def test_engine_split_protocol():
    t6 = np.zeros((2, 6, 8), np.float32)
    t6[:, 5, :5] = [0.5, 0.1, 0.4, 0.9, 0.8]
    tables, quality = BatchMatchEngine.split(t6)
    assert tables.shape == (2, 5, 8)
    assert quality[1]["score"] == pytest.approx(0.5)
    assert quality[0]["coherence"] == pytest.approx(0.8)
    t5 = np.zeros((2, 5, 8), np.float32)
    tables, quality = BatchMatchEngine.split(t5)
    assert tables.shape == (2, 5, 8) and quality is None
    with pytest.raises(ValueError):
        BatchMatchEngine.split(np.zeros((2, 7, 8), np.float32))


# ---------------------------------------------------------------------------
# serving correctness: the real tiny engine
# ---------------------------------------------------------------------------


def test_service_serves_real_matches_with_quality(tiny_params):
    """The served table equals the warm point matcher's output for the same
    pair (no pad: 32-aligned input), and the per-request quality dict is
    the same signal set the matcher emits."""
    # 64 px: the 4x4 feature grid gives N=16 match cells, wide enough for
    # the 5-signal quality row (a 2x2 grid would drop it by design)
    src, tgt = u8(64, 1), u8(64, 2)
    svc = MatchService(TINY, tiny_params, ServingConfig(
        bucket_multiple=32, max_image_side=64)).start()
    try:
        res = svc.submit(src, tgt).result(timeout=120)
    finally:
        svc.stop()
    matcher = models.make_point_matcher(TINY, tiny_params, do_softmax=True)
    want, want_q = matcher.match_with_quality(src[None], tgt[None])
    for got_row, want_row in zip(res.table, want):
        np.testing.assert_allclose(
            got_row, np.asarray(want_row, np.float32)[0], atol=1e-5)
    assert set(res.quality) == set(want_q)
    for name, v in want_q.items():
        assert res.quality[name] == pytest.approx(v, abs=1e-5)
    assert svc.health()["counters"]["results"] == 1


def test_point_matcher_quality_is_per_call(tiny_params):
    """Satellite fix: quality travels WITH each fetched result — two
    in-flight pairs cannot read each other's signals (the old
    ``last_quality`` closure attribute was last-write-wins)."""
    matcher = models.make_point_matcher(TINY, tiny_params, do_softmax=True)
    a1, a2 = u8(64, 3)[None], u8(64, 4)[None]
    h1 = matcher.dispatch(a1, a1)
    h2 = matcher.dispatch(a2, a2)
    m1, q1 = matcher.fetch_with_quality(h1)
    m2, q2 = matcher.fetch_with_quality(h2)
    assert q1 is not None and q2 is not None and q1 != q2
    # the legacy attribute still tracks the LAST fetch (demo convenience)
    assert matcher.last_quality == q2
    # plain fetch keeps its old signature
    assert len(matcher.fetch(matcher.dispatch(a1, a1))) == 5
    # and the one-shot with-quality call matches its parts
    m, q = matcher.match_with_quality(a1, a1)
    assert q == pytest.approx(q1, abs=1e-6)


def test_two_resolutions_two_buckets(tiny_params):
    """Variable-resolution queries coalesce into distinct padded buckets,
    both served; the bucket rides on the result."""
    svc = MatchService(TINY, tiny_params, ServingConfig(
        bucket_multiple=32, max_image_side=64, max_buckets=4)).start()
    try:
        f1 = svc.submit(u8(32, 1), u8(32, 2))
        f2 = svc.submit(u8(40, 3), u8(40, 4))  # pads to 64
        r1, r2 = f1.result(timeout=120), f2.result(timeout=120)
    finally:
        svc.stop()
    assert r1.bucket == ((32, 32), (32, 32))
    assert r2.bucket == ((64, 64), (64, 64))
    assert sorted(svc.health()["queue"]["buckets"]) == ["32x32-32x32", "64x64-64x64"]


# ---------------------------------------------------------------------------
# batching, admission, deadlines (fake device)
# ---------------------------------------------------------------------------


def test_continuous_batching_coalesces(tmp_path):
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc, eng = fake_service(latency_s=0.05)
        svc.start()
        img = u8()
        futs = [svc.submit(img, img) for _ in range(12)]
        for f in futs:
            f.result(timeout=30)
        svc.stop()
    _, events = obs_events.replay_events(log_path)
    sizes = [e["size"] for e in events if e["event"] == "serve_batch"]
    assert sum(sizes) == 12
    # the queue builds while a batch is in flight; later dispatches coalesce
    assert max(sizes) >= 2
    assert len(sizes) < 12
    # the batch dim the DEVICE sees is bucketed to a power-of-two ladder —
    # otherwise every coalesced size 1..max_batch compiles its own program
    assert set(eng.batch_sizes) <= {1, 2, 4, 8}


def test_overload_burst_sheds_never_deadline_blows_admitted(tmp_path):
    """Acceptance (c): a burst beyond the queue bound sheds with classified
    Overloaded + retry-after, and every ADMITTED request still resolves as
    a result (the bound is what protects admitted work's latency)."""
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc, eng = fake_service(latency_s=0.03, max_queue=4, max_batch=2,
                                default_deadline_s=20.0)
        svc.start()
        img = u8()
        futs, sheds = queue_overflow_burst(
            lambda: svc.submit(img, img), 30)
        outcomes = []
        for f in futs:
            f.result(timeout=30)
            outcomes.append(f.outcome)
        svc.stop()
    assert sheds, "a 30-deep burst against a 4-deep queue must shed"
    assert all(s.reason == "queue_full" for s in sheds)
    assert all(s.retry_after_s and s.retry_after_s > 0 for s in sheds)
    assert all(o == "result" for o in outcomes)
    _, events = obs_events.replay_events(log_path)
    sec = run_report.build_serving_section(events)
    assert sec["outcomes"]["deadline_exceeded"] == 0
    assert sec["outcomes"]["unresolved"] == 0
    assert sec["shed_reasons"]["queue_full"] == len(sheds)


def test_per_client_cap_isolates_misbehaving_client():
    svc, eng = fake_service(latency_s=0.1, max_queue=32,
                            max_in_flight_per_client=2)
    svc.start()
    try:
        img = u8()
        futs, sheds = [], []
        for _ in range(6):
            try:
                futs.append(svc.submit(img, img, client="noisy"))
            except Overloaded as e:
                sheds.append(e)
        assert sheds and all(s.reason == "client_cap" for s in sheds)
        # the polite client is unaffected by the noisy one's cap
        ok = svc.submit(img, img, client="polite")
        assert ok.result(timeout=30).request_id
        for f in futs:
            f.result(timeout=30)
    finally:
        svc.stop()


def test_deadline_checked_at_admission_dequeue_and_fetch(tmp_path):
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc, eng = fake_service(latency_s=0.25, pipeline_depth=1,
                                max_batch=1)
        svc.start()
        img = u8()
        # admission: an already-spent budget is refused synchronously
        with pytest.raises(DeadlineExceeded) as e:
            svc.submit(img, img, deadline_s=0)
        assert e.value.where == "admission"
        # dequeue: r2 expires while r1's batch occupies the (depth-1)
        # pipeline — evicted before dispatch, never wasting a device slot
        f1 = svc.submit(img, img)
        time.sleep(0.02)  # let the worker take r1 in flight first
        f2 = svc.submit(img, img, deadline_s=0.05)
        with pytest.raises(DeadlineExceeded) as e:
            f2.result(timeout=30)
        assert e.value.where == "dequeue"
        assert f1.result(timeout=30)
        eng2_dispatches = eng.dispatches
        # fetch: the result lands after the caller's budget — classified,
        # not returned as a zombie success.  The idle worker dispatches in
        # ms, far under the 0.2 s budget; the 0.5 s fetch blows it.
        eng.latency_s = 0.5
        f3 = svc.submit(img, img, deadline_s=0.2)
        with pytest.raises(DeadlineExceeded) as e:
            f3.result(timeout=30)
        assert e.value.where == "fetch"
        assert eng.dispatches == eng2_dispatches + 1
        svc.stop()
    _, events = obs_events.replay_events(log_path)
    sec = run_report.build_serving_section(events)
    assert sec["deadline_where"] == {"admission": 1, "dequeue": 1,
                                     "fetch": 1}
    # admission-refused budgets were never admitted; accounting stays total
    assert sec["outcomes"]["admitted"] == 3
    assert sec["outcomes"]["unresolved"] == 0


# ---------------------------------------------------------------------------
# failure handling: demotion, quarantine, hung fetch
# ---------------------------------------------------------------------------


def test_device_failure_demotes_and_keeps_serving_zero_lost(
        tmp_path, tiny_params):
    """Acceptance (a): sustained stream → injected device failure
    mid-stream → tier demoted, service DEGRADED but serving, every admitted
    request reaches exactly one terminal outcome (event-log accounting),
    zero lost."""
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc = MatchService(TINY, tiny_params, ServingConfig(
            bucket_multiple=32, max_image_side=64, max_batch=2,
            quarantine_dir=str(tmp_path / "q"))).start()
        # ordinal 2: the SECOND dispatched batch fails mid-stream
        faults.install(FaultPlan(device_fail_calls=(2,)))
        try:
            futs = [svc.submit(u8(32, i), u8(32, i + 100))
                    for i in range(8)]
            outcomes = []
            for f in futs:
                f.result(timeout=180)
                outcomes.append(f.outcome)
            health = svc.health()
        finally:
            faults.clear()
            svc.stop()
    assert all(o == "result" for o in outcomes)
    assert health["state"] == DEGRADED
    assert ops.demoted_fused_tiers()  # the ladder actually moved
    _, events = obs_events.replay_events(log_path)
    sec = run_report.build_serving_section(events)
    assert sec["outcomes"]["admitted"] == 8
    assert sec["outcomes"]["results"] == 8
    assert sec["outcomes"]["unresolved"] == 0 and not sec["lost_requests"]
    # the off-budget recovery retry is in the log, attributed to serving
    retries = [e for e in events if e.get("event") == "retry"
               and e.get("scope") == "serving"]
    assert retries and all(e["on_budget"] is False for e in retries)
    assert any(e.get("event") == "serve_health"
               and e.get("state") == DEGRADED for e in events)
    # nothing quarantined: the manifest stays empty
    from ncnet_tpu.evaluation.resilience import manifest_has_quarantined

    assert not manifest_has_quarantined(
        str(tmp_path / "q" / "manifest.json"))


def test_exhausted_failures_quarantine_and_stream_continues(
        tmp_path, tiny_params):
    """With every tier already demoted (nothing left to recover with) and
    the retry budget at zero, a persistently failing request quarantines —
    into the manifest AND as a classified future error — while the next
    request serves normally."""
    while ops.demote_fused_tier() is not None:
        pass
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc = MatchService(TINY, tiny_params, ServingConfig(
            bucket_multiple=32, max_image_side=64, retries=0,
            quarantine_dir=str(tmp_path / "q"))).start()
        faults.install(FaultPlan(device_fail_calls=tuple(range(1, 10))))
        try:
            f = svc.submit(u8(32, 1), u8(32, 2))
            with pytest.raises(RequestQuarantined) as e:
                f.result(timeout=120)
            assert e.value.kind == "device" and f.outcome == "quarantined"
            faults.clear()
            ok = svc.submit(u8(32, 3), u8(32, 4))
            assert ok.result(timeout=120).table.shape[0] == 5
        finally:
            faults.clear()
            svc.stop()
    from ncnet_tpu.evaluation.resilience import RunManifest

    m = RunManifest(str(tmp_path / "q" / "manifest.json"),
                    meta={"scope": "serving"})
    assert m.data["quarantined"] and \
        list(m.data["quarantined"].values())[0]["kind"] == "device"
    _, events = obs_events.replay_events(log_path)
    sec = run_report.build_serving_section(events)
    assert sec["outcomes"]["quarantined"] == 1
    assert sec["outcomes"]["results"] == 1
    assert sec["outcomes"]["unresolved"] == 0


def test_recovery_crash_falls_back_to_retry_budget(monkeypatch):
    """If the tier-recovery path ITSELF raises, the worker must not die
    (taking every queued request with it): the failure falls back to the
    plain retry budget and the request still completes."""
    import ncnet_tpu.models.ncnet as ncnet_mod

    def boom(exc, *retraceables, **kw):
        raise RuntimeError("recovery exploded")

    monkeypatch.setattr(ncnet_mod, "recover_from_device_failure", boom)
    svc, eng = fake_service(max_batch=1, retries=1)
    svc.start()
    faults.install(FaultPlan(device_fail_calls=(1,)))
    try:
        f = svc.submit(u8(), u8())
        assert f.result(timeout=30).request_id
        assert f.outcome == "result"
        assert svc.state == READY  # no crash, no spurious DEGRADED
    finally:
        faults.clear()
        svc.stop()


def test_hung_fetch_surfaces_as_timeout_and_retries(tmp_path):
    """A hung tunnel fetch (injected) overruns the fetch watchdog, is
    classified 'timeout', charged to the budget, and the retried batch
    completes — the stream never wedges."""
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc, eng = fake_service(latency_s=0.0, fetch_timeout_s=0.3,
                                retries=1)
        svc.start()
        faults.install(FaultPlan(hang_fetch_calls=(1,),
                                 hang_fetch_seconds=1.5))
        try:
            t0 = time.monotonic()
            f = svc.submit(u8(), u8())
            res = f.result(timeout=30)
            assert res.request_id and f.outcome == "result"
            assert time.monotonic() - t0 < 10
        finally:
            faults.clear()
            svc.stop()
    _, events = obs_events.replay_events(log_path)
    assert any(e.get("event") == "watchdog_timeout" for e in events)
    retries = [e for e in events if e.get("event") == "retry"
               and e.get("scope") == "serving"]
    assert retries and retries[0]["kind"] == "timeout" \
        and retries[0]["on_budget"] is True


# ---------------------------------------------------------------------------
# drain: SIGTERM, kill-mid-drain
# ---------------------------------------------------------------------------


def test_sigterm_drains_in_flight_then_stops(tmp_path):
    """Acceptance (b): SIGTERM → admission closes, every admitted request
    completes, the drain event lands, the exit is clean."""
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc, eng = fake_service(latency_s=0.04, max_batch=2,
                                install_sigterm=True)
        svc.start()
        img = u8()
        futs = [svc.submit(img, img) for _ in range(6)]
        os.kill(os.getpid(), signal.SIGTERM)
        for f in futs:
            f.result(timeout=30)
        # the worker notices the flag, drains, and stops on its own
        deadline = time.monotonic() + 10
        while svc.state != STOPPED and time.monotonic() < deadline:
            time.sleep(0.02)
        assert svc.state == STOPPED
        with pytest.raises(Overloaded) as e:
            svc.submit(img, img)
        assert e.value.reason in ("draining", "stopped")
        svc.stop()  # restores the old handler; worker already gone
    assert all(f.outcome == "result" for f in futs)
    _, events = obs_events.replay_events(log_path)
    drains = [e for e in events if e.get("event") == "serve_drain"]
    assert len(drains) == 1 and drains[0]["drained"] is True \
        and drains[0]["leftover"] == 0
    states = [e["state"] for e in events if e.get("event") == "serve_health"]
    assert states[-2:] == [DRAINING, STOPPED]
    sec = run_report.build_serving_section(events)
    assert sec["outcomes"]["unresolved"] == 0


def test_device_failure_during_drain_still_completes(tmp_path):
    """A device failure while DRAINING must not fight the lifecycle state
    machine (DRAINING -> DEGRADED is illegal): the tier still demotes, the
    batch still requeues off-budget, and the drain guarantee — every
    admitted request completes — holds."""
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc, eng = fake_service(latency_s=0.03, max_batch=1,
                                pipeline_depth=1)
        svc.start()
        img = u8()
        faults.install(FaultPlan(device_fail_calls=(3,)))
        try:
            futs = [svc.submit(img, img) for _ in range(6)]
            svc.request_drain()
            for f in futs:
                f.result(timeout=30)
        finally:
            faults.clear()
            svc.stop(timeout=30)
    assert all(f.outcome == "result" for f in futs)
    assert eng.retraces == 1  # the recovery really ran, mid-drain
    assert ops.demoted_fused_tiers()
    _, events = obs_events.replay_events(log_path)
    sec = run_report.build_serving_section(events)
    assert sec["outcomes"]["unresolved"] == 0
    states = [e["state"] for e in events if e.get("event") == "serve_health"]
    assert DEGRADED not in states  # no illegal DRAINING -> DEGRADED edge
    assert states[-2:] == [DRAINING, STOPPED]


def test_abort_stop_settles_queued_work_classified(tmp_path):
    """stop(drain=False) is still outcome-total: queued work settles
    Overloaded(reason='shutdown'), never a hang or a silent drop — and the
    serve_drain event says drained=False (an abort that rejected admitted
    work must stay distinguishable from a clean drain)."""
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc, eng = fake_service(latency_s=0.2, max_batch=1,
                                pipeline_depth=1)
        svc.start()
        img = u8()
        futs = [svc.submit(img, img) for _ in range(5)]
        svc.stop(drain=False, timeout=30)
    outcomes = set()
    for f in futs:
        try:
            f.result(timeout=10)
            outcomes.add("result")
        except Overloaded as e:
            assert e.reason == "shutdown"
            outcomes.add("overloaded")
    assert "overloaded" in outcomes  # the tail was aborted, classified
    assert all(f.outcome is not None for f in futs)
    _, events = obs_events.replay_events(log_path)
    drains = [e for e in events if e.get("event") == "serve_drain"]
    assert len(drains) == 1 and drains[0]["drained"] is False \
        and drains[0]["leftover"] > 0


_KILL_MID_DRAIN_CHILD = """
import os, sys, time
import numpy as np

sys.path.insert(0, {repo!r})
from ncnet_tpu.observability import EventLog
from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.serving import MatchService, ServingConfig
from ncnet_tpu.serving.engine import BatchMatchEngine


class FakeEngine:
    split = staticmethod(BatchMatchEngine.split)
    half_precision = False

    def dispatch(self, s, t):
        return s.shape[0]

    def fetch(self, b):
        time.sleep(0.03)
        tab = np.zeros((b, 6, 16), np.float32)
        tab[:, 5, :5] = 0.5
        return tab

    def retrace(self):
        pass


obs_events.set_global_sink(EventLog(sys.argv[1]))
svc = MatchService(engine=FakeEngine(), serving=ServingConfig(
    bucket_multiple=32, max_image_side=64, max_batch=1,
    pipeline_depth=1)).start()
img = np.zeros((32, 32, 3), np.uint8)
futs = [svc.submit(img, img) for _ in range(8)]
svc.request_drain()
svc.stop(timeout=60)
sys.stdout.write("CLEAN\\n")
"""


def test_kill_mid_drain_event_log_accounts_for_losses(tmp_path):
    """Acceptance (d): SIGKILL after the 3rd terminal outcome of the drain.
    The fsynced event log survives; replayed accounting identifies exactly
    the admitted requests that died without an outcome — they are named,
    not silently lost."""
    log_path = str(tmp_path / "events.jsonl")
    child = tmp_path / "child.py"
    child.write_text(_KILL_MID_DRAIN_CHILD.format(repo=_REPO))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               NCNET_TPU_PERF_STORE="off", NCNET_TPU_TIER_CACHE="off",
               NCNET_TPU_FAULTS=json.dumps({"kill_at_drain_result": 3}))
    proc = subprocess.run(
        [sys.executable, str(child), log_path],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stderr
    assert "CLEAN" not in proc.stdout
    _, events = obs_events.replay_events(log_path)
    sec = run_report.build_serving_section(events)
    assert sec["outcomes"]["admitted"] == 8
    # >= 3 terminals made it to disk before the kill; the rest are NAMED
    assert sec["outcomes"]["terminals"] >= 3
    assert sec["outcomes"]["unresolved"] == len(sec["lost_requests"]) > 0
    # the tool renders the degraded log end to end
    assert run_report.main([log_path, "--serving", "--json"]) == 0


# ---------------------------------------------------------------------------
# liveness: heartbeat + stall watchdog (satellite)
# ---------------------------------------------------------------------------


def test_stall_watchdog_flags_wedged_service_green_under_load(tmp_path):
    """The service beats the heartbeat once per dispatched batch; the
    stall watchdog derives its threshold from the serve_batch cadence in
    the sibling event log — green under load, STALLED while a hung fetch
    wedges the pipeline, green again after recovery."""
    hb = str(tmp_path / "heartbeat.json")
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc, eng = fake_service(latency_s=0.02, max_batch=1,
                                pipeline_depth=1, heartbeat_path=hb)
        svc.start()
        img = u8()
        for f in [svc.submit(img, img) for _ in range(10)]:
            f.result(timeout=30)
        # under load: fresh beats, cadence-derived threshold, alive
        v = stall_watchdog.judge(hb, events_path=log_path, factor=5,
                                 min_age=0.4)
        assert v["status"] == "alive"
        assert v["median_step_wall_s"] is not None  # serve_batch cadence
        # wedge the device: the next fetch hangs; beats stop.  The wait
        # must clear factor x median even when suite load inflates the
        # recorded batch walls — 2 s vs 5 x ~0.02-0.1 s leaves margin
        eng.latency_s = 30.0
        svc.submit(img, img)
        time.sleep(2.0)
        v = stall_watchdog.judge(hb, events_path=log_path, factor=5,
                                 min_age=0.4)
        assert v["status"] == "stalled"
        # release the wedge: the blocked fetch returns, beats resume with
        # the next dispatched batches and the verdict recovers
        eng.latency_s = 0.0
        for f in [svc.submit(img, img) for _ in range(3)]:
            f.result(timeout=30)
        assert stall_watchdog.judge(
            hb, events_path=log_path, factor=5,
            min_age=0.4)["status"] == "alive"
        svc.stop(timeout=30)


# ---------------------------------------------------------------------------
# tools: probe smoke, report rendering
# ---------------------------------------------------------------------------


def test_serve_probe_tiny_smoke(tmp_path, capsys):
    import serve_probe

    out_path = str(tmp_path / "probe.json")
    rc = serve_probe.main(["--tiny", "--sides", "32", "--pairs", "4",
                           "--no-demote", "--burst-factor", "1.0",
                           "--json", out_path])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    with open(out_path) as f:
        assert json.load(f) == doc
    assert "32x32" in doc["buckets"]
    assert doc["buckets"]["32x32"]["latency_ms"]["n"] == 4
    assert doc["burst"]["offered"] >= 32
    assert doc["health"]["counters"]["results"] >= 4


def test_run_report_serving_text_render(tmp_path, capsys):
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc, eng = fake_service(latency_s=0.01)
        svc.start()
        img = u8()
        for f in [svc.submit(img, img) for _ in range(3)]:
            f.result(timeout=30)
        svc.stop()
    assert run_report.main([log_path, "--serving"]) == 0
    out = capsys.readouterr().out
    assert "serving:" in out
    assert "exactly one terminal outcome" in out
    assert "admitted=3" in out and "results=3" in out
