"""Tests for the localization stage (the reference's MATLAB L6 pipeline).

Oracles are synthetic scenes with known geometry: poses are drawn at random,
3D points projected exactly, and every estimator must recover what generated
its input — the strategy SURVEY §4 prescribes for reference-free components.
"""

import os

import numpy as np
import pytest

from ncnet_tpu.localization import geometry
from ncnet_tpu.localization.p3p import (
    lo_ransac_p3p,
    p3p_solve,
    refine_pose_object_space,
)


def random_pose(rng, depth=4.0):
    A = rng.normal(size=(3, 3))
    Q, _ = np.linalg.qr(A)
    if np.linalg.det(Q) < 0:
        Q[:, 0] *= -1
    t = rng.normal(size=3) * 0.3 + np.array([0.0, 0.0, depth])
    return np.concatenate([Q, t[:, None]], axis=1)


def rays_for(P, X):
    xc = X @ P[:, :3].T + P[:, 3]
    return xc / np.linalg.norm(xc, axis=1, keepdims=True)


class TestGeometry:
    def test_camera_center_roundtrip(self, rng):
        P = random_pose(rng)
        C = geometry.camera_center(P)
        # projecting the center must give the zero vector in camera frame
        np.testing.assert_allclose(P[:, :3] @ C + P[:, 3], 0.0, atol=1e-12)

    def test_pose_distance_identity(self, rng):
        P = random_pose(rng)
        dp, do = geometry.pose_distance(P, P)
        assert dp == pytest.approx(0.0, abs=1e-12)
        assert do == pytest.approx(0.0, abs=1e-6)

    def test_iphone7_focal_orientation_invariant(self):
        # the 35mm-equivalence is against the sensor LONG side: portrait- and
        # landscape-stored copies of the same photo share one focal length
        f_port = geometry.iphone7_focal(4032, 3024)
        f_land = geometry.iphone7_focal(3024, 4032)
        assert f_port == f_land == pytest.approx(4032 * 28.0 / 36.0)

    def test_pose_distance_known_rotation(self):
        P1 = np.concatenate([np.eye(3), np.zeros((3, 1))], axis=1)
        ang = 0.3
        R = np.array(
            [
                [np.cos(ang), -np.sin(ang), 0.0],
                [np.sin(ang), np.cos(ang), 0.0],
                [0.0, 0.0, 1.0],
            ]
        )
        P2 = np.concatenate([R, np.zeros((3, 1))], axis=1)
        dp, do = geometry.pose_distance(P1, P2)
        assert dp == pytest.approx(0.0, abs=1e-12)
        assert do == pytest.approx(ang, abs=1e-9)

    def test_project_pixel_rays_roundtrip(self, rng):
        P = random_pose(rng)
        K = geometry.intrinsics(500.0, 480, 640)
        X = rng.uniform(-1, 1, (50, 3))
        xy, depth = geometry.project_points(P, K, X)
        assert np.all(depth > 0)
        rays = geometry.pixel_rays(K, xy)
        xc = X @ P[:, :3].T + P[:, 3]
        cos = np.sum(rays * xc / np.linalg.norm(xc, axis=1, keepdims=True), 1)
        np.testing.assert_allclose(cos, 1.0, atol=1e-12)

    def test_cap_longest_side(self):
        # at_imageresize_nc4d.m: cap 1920, aspect preserved, never upscale
        assert geometry.cap_longest_side_shape(3840, 2880) == (1920, 1440)
        assert geometry.cap_longest_side_shape(2880, 3840) == (1440, 1920)
        assert geometry.cap_longest_side_shape(1000, 800) == (1000, 800)


class TestP3P:
    def test_minimal_recovers_pose(self, rng):
        for _ in range(20):
            P = random_pose(rng)
            X = rng.uniform(-1, 1, (3, 3))
            sols = p3p_solve(rays_for(P, X)[None], X[None])[0]
            errs = [
                sum(geometry.pose_distance(P, s))
                for s in sols
                if np.isfinite(s[0, 0])
            ]
            assert errs and min(errs) < 1e-6

    def test_ransac_with_outliers(self, rng):
        P = random_pose(rng)
        N = 300
        X = rng.uniform(-2, 2, (N, 3))
        rays = rays_for(P, X)
        out = rng.random(N) < 0.4
        bad = rng.normal(size=(out.sum(), 3))
        rays[out] = bad / np.linalg.norm(bad, axis=1, keepdims=True)
        res = lo_ransac_p3p(rays, X, np.deg2rad(0.2), iters=500, seed=1)
        dp, do = geometry.pose_distance(P, res.P)
        assert dp < 1e-6 and do < 1e-8
        np.testing.assert_array_equal(res.inliers, ~out)

    def test_ransac_with_noise(self, rng):
        P = random_pose(rng)
        K = geometry.intrinsics(800.0, 600, 800)
        X = rng.uniform(-2, 2, (200, 3))
        xy, _ = geometry.project_points(P, K, X)
        xy += rng.normal(scale=0.5, size=xy.shape)  # half-pixel noise
        rays = geometry.pixel_rays(K, xy)
        res = lo_ransac_p3p(rays, X, np.deg2rad(0.2), iters=500, seed=2)
        dp, do = geometry.pose_distance(P, res.P)
        assert dp < 0.05 and np.rad2deg(do) < 0.5
        assert res.num_inliers > 100

    def test_ransac_degenerate_input(self):
        res = lo_ransac_p3p(np.zeros((2, 3)), np.zeros((2, 3)), 0.01, iters=10)
        assert np.all(np.isnan(res.P))
        assert res.num_inliers == 0

    def test_refinement_improves_noisy_pose(self, rng):
        P = random_pose(rng)
        X = rng.uniform(-2, 2, (100, 3))
        rays = rays_for(P, X)
        # perturb: small rotation + translation offset
        d = 0.05
        Rp = np.array(
            [[np.cos(d), -np.sin(d), 0], [np.sin(d), np.cos(d), 0], [0, 0, 1]]
        )
        P0 = np.concatenate(
            [Rp @ P[:, :3], P[:, 3:] + rng.normal(scale=0.05, size=(3, 1))], 1
        )
        P_ref = refine_pose_object_space(rays, X, P0)
        dp0, do0 = geometry.pose_distance(P, P0)
        dp1, do1 = geometry.pose_distance(P, P_ref)
        assert dp1 < dp0 * 0.01 and do1 < do0 * 0.01


class TestScan:
    def test_parse_cutout_name(self):
        from ncnet_tpu.localization.scan import parse_cutout_name

        info = parse_cutout_name("DUC1/DUC_cutout_024_30_0.jpg")
        assert info == ("DUC1", "DUC", "024")

    def test_paths(self):
        from ncnet_tpu.localization.scan import scan_path, transformation_path

        name = "DUC2/DUC_cutout_005_120_30.jpg"
        assert transformation_path("/d", name) == (
            "/d/DUC2/transformations/DUC_trans_005.txt"
        )
        assert scan_path("/s", name) == "/s/DUC2/DUC_scan_005.ptx.mat"

    def test_load_transformation(self, tmp_path):
        from ncnet_tpu.localization.scan import load_transformation

        M1 = np.arange(16, dtype=float).reshape(4, 4)
        M2 = np.linalg.inv(np.eye(4) + 0.1)
        path = tmp_path / "t.txt"
        with open(path, "w") as f:
            f.write("WUSTL transformation file\nheader line two\n")
            for row in M1:
                f.write(" ".join(f"{v:.8f}" for v in row) + "\n")
            f.write("P_after:\n")
            for row in M2:
                f.write(" ".join(f"{v:.8f}" for v in row) + "\n")
        np.testing.assert_allclose(load_transformation(str(path)), M2, atol=1e-7)

    def test_backproject_semantics(self):
        from ncnet_tpu.localization.scan import backproject_matches

        H, W = 10, 20
        gx, gy = np.meshgrid(np.arange(W), np.arange(H), indexing="xy")
        xyz = np.stack(
            [gx, gy, np.ones((H, W))], axis=2
        ).astype(float)  # pixel (r,c) holds [c, r, 1]
        xyz[0, 0] = np.nan  # a hole
        P_after = np.eye(4)
        # the reference gather: 1-based floor(size·coord), zeros bumped to 1
        xy = np.array(
            [
                [0.0, 0.0],          # floor→0, bumped → 0-based pixel (0,0): NaN hole
                [(5 + 1.2) / W, (3 + 1.2) / H],  # lands on 0-based (3,5)
                [1.0, 1.0],          # clamps to the last pixel
            ]
        )
        pts, keep, px = backproject_matches(xyz, xy, P_after)
        np.testing.assert_array_equal(keep, [False, True, True])
        np.testing.assert_array_equal(px[1], [5, 3])
        np.testing.assert_allclose(pts[0], [5.0, 3.0, 1.0])
        np.testing.assert_allclose(pts[1], [W - 1.0, H - 1.0, 1.0])

    def test_transform_points_homogeneous(self, rng):
        from ncnet_tpu.localization.scan import transform_points

        T = np.eye(4)
        T[:3, :3] = random_pose(rng)[:, :3]
        T[:3, 3] = [1.0, -2.0, 0.5]
        X = rng.normal(size=(20, 3))
        np.testing.assert_allclose(
            transform_points(T, X), X @ T[:3, :3].T + T[:3, 3], atol=1e-12
        )


class TestRender:
    def test_zbuffer_occlusion_and_nan(self):
        from ncnet_tpu.localization.render import render_points_perspective

        K = geometry.intrinsics(50.0, 40, 60)
        KP = K @ np.concatenate([np.eye(3), np.zeros((3, 1))], axis=1)
        # two points on the same ray: the nearer must win
        ray = np.linalg.inv(K) @ np.array([30.0, 20.0, 1.0])
        pts = np.stack([ray * 5.0, ray * 2.0])
        rgb = np.array([[10, 10, 10], [200, 0, 0]], dtype=np.uint8)
        img, xyz = render_points_perspective(rgb, pts, KP, 40, 60)
        np.testing.assert_array_equal(img[20, 30], [200, 0, 0])
        assert xyz[20, 30, 2] == pytest.approx(2.0)
        # everything else is a NaN hole / zero color
        assert np.isnan(xyz[0, 0]).all() and (img[0, 0] == 0).all()

    def test_behind_camera_ignored(self):
        from ncnet_tpu.localization.render import render_points_perspective

        K = geometry.intrinsics(50.0, 40, 60)
        KP = K @ np.concatenate([np.eye(3), np.zeros((3, 1))], axis=1)
        img, xyz = render_points_perspective(
            np.array([[255, 255, 255]], np.uint8),
            np.array([[0.0, 0.0, -3.0]]),
            KP, 40, 60,
        )
        assert np.isnan(xyz[..., 0]).all()


class TestDSIFT:
    def test_descriptor_shape_and_norm(self, rng):
        from ncnet_tpu.localization.dsift import (
            dense_sift,
            descriptor_grid,
            rootsift,
        )

        img = rng.random((120, 160))
        d = dense_sift(img)
        ys, xs = descriptor_grid(120, 160)
        assert d.shape == (len(ys), len(xs), 128)
        norms = np.linalg.norm(d, axis=-1)
        np.testing.assert_allclose(norms, 1.0, atol=1e-4)
        r = rootsift(d)
        np.testing.assert_allclose(np.linalg.norm(r, axis=-1), 1.0, atol=1e-4)

    def test_score_prefers_matching_image(self, rng):
        from ncnet_tpu.localization.dsift import pose_verification_score

        img = rng.random((120, 160))
        mask = np.ones((120, 160), bool)
        # photometric affine change must not hurt (masked normalization)
        s_same = pose_verification_score(img, img * 2.0 + 1.0, mask)
        s_shift = pose_verification_score(img, np.roll(img, 8, axis=1), mask)
        assert s_same > s_shift > 0

    def test_score_empty_mask_is_zero(self, rng):
        from ncnet_tpu.localization.dsift import pose_verification_score

        img = rng.random((64, 64))
        assert pose_verification_score(img, img, np.zeros((64, 64), bool)) == 0.0

    def test_inpaint_fills_holes(self, rng):
        from ncnet_tpu.localization.dsift import inpaint_nans

        img = np.ones((30, 30))
        img[10:15, 10:15] = np.nan
        out = inpaint_nans(img)
        assert np.isfinite(out).all()
        np.testing.assert_allclose(out, 1.0, atol=1e-6)


class TestCurves:
    def test_rates_and_gating(self, tmp_path):
        from ncnet_tpu.localization.curves import (
            ERROR_THRESHOLDS,
            MethodResult,
            localized_rate_curve,
            plot_localization_curves,
            pose_errors,
        )

        eye = np.concatenate([np.eye(3), np.zeros((3, 1))], axis=1)

        def shifted(dx, ang=0.0):
            R = np.array(
                [
                    [np.cos(ang), -np.sin(ang), 0],
                    [np.sin(ang), np.cos(ang), 0],
                    [0, 0, 1],
                ]
            )
            return np.concatenate([R, -R @ np.array([[dx], [0], [0]])], axis=1)

        refposes = {
            "DUC1": {"q1.jpg": eye, "q2.jpg": eye},
            "DUC2": {"q3.jpg": eye, "q4.jpg": eye},
        }
        method = MethodResult(
            "test",
            {
                "q1.jpg": ("DUC1/a.jpg", shifted(0.10)),   # 10 cm error
                "q2.jpg": ("DUC2/a.jpg", shifted(0.10)),   # wrong floor → inf
                "q3.jpg": ("DUC2/a.jpg", shifted(0.50)),   # 50 cm error
                "q4.jpg": ("DUC2/a.jpg", shifted(0.05, np.deg2rad(20))),
                # orientation 20° > 10° gate → rejected
            },
        )
        poserr, orierr, names = pose_errors(method, refposes)
        assert len(names) == 4
        curve = localized_rate_curve(poserr, orierr)
        # thresholds: at 0.25m only q1 counts (q2 wrong floor, q4 gated)
        i25 = np.abs(ERROR_THRESHOLDS - 0.25).argmin()
        i75 = np.abs(ERROR_THRESHOLDS - 0.75).argmin()
        assert curve[i25] == pytest.approx(0.25)
        assert curve[i75] == pytest.approx(0.5)  # q1 + q3
        curves = plot_localization_curves([method], refposes, str(tmp_path))
        np.testing.assert_allclose(curves["test"], curve)
        assert os.path.exists(tmp_path / "error_test.txt")
        assert len(open(tmp_path / "error_test.txt").read().splitlines()) == 4
        assert any(f.suffix == ".png" for f in tmp_path.iterdir())


def make_plane_scene(rng, n=160, m=200):
    """A textured plane z=5 (global coords): grid points + smooth colors."""
    xs = np.linspace(-2.4, 2.4, m)
    ys = np.linspace(-1.8, 1.8, n)
    gx, gy = np.meshgrid(xs, ys, indexing="xy")
    pts = np.stack([gx, gy, np.full_like(gx, 5.0)], axis=2).reshape(-1, 3)
    # smooth random texture (low-frequency so SIFT sees gradients, not noise)
    base = rng.random((n // 8 + 2, m // 8 + 2, 3))
    from ncnet_tpu.ops.image import resize_bilinear_align_corners_np

    tex = resize_bilinear_align_corners_np(
        base.astype(np.float32), n, m
    ).reshape(-1, 3)
    rgb = np.clip(tex * 255, 0, 255).astype(np.uint8)
    return pts, rgb


class TestPnPPipeline:
    def test_estimate_pose_from_matches(self, rng, tmp_path):
        from ncnet_tpu.localization.pnp import estimate_pose_from_matches

        # db camera at origin; its XYZcut holds plane points per pixel
        Hdb, Wdb = 60, 80
        Kdb = geometry.intrinsics(70.0, Hdb, Wdb)
        inv = np.linalg.inv(Kdb)
        cols, rows = np.meshgrid(np.arange(Wdb), np.arange(Hdb), indexing="xy")
        d = 5.0
        pix = np.stack([cols, rows, np.ones_like(cols)], axis=2).astype(float)
        xyzcut = (pix @ inv.T) * d  # depth-5 plane in db-local coords
        T = np.eye(4)  # scan-local == global for this test
        # query pose: near the db camera, mildly rotated, still facing the
        # plane (a fully random orientation would see none of it)
        ang = 0.1
        Rq = np.array(
            [
                [np.cos(ang), 0, np.sin(ang)],
                [0, 1, 0],
                [-np.sin(ang), 0, np.cos(ang)],
            ]
        )
        P_q = np.concatenate(
            [Rq, np.array([[0.2], [-0.15], [0.3]])], axis=1
        )
        qh, qw = 300, 400
        focal = 350.0
        Kq = geometry.intrinsics(focal, qh, qw)
        # pick db pixels, build normalized match rows
        sel = rng.choice(Hdb * Wdb, size=150, replace=False)
        r_sel, c_sel = sel // Wdb, sel % Wdb
        X = xyzcut[r_sel, c_sel]
        xy_q, depth = geometry.project_points(P_q, Kq, X)
        ok = (
            (depth > 0)
            & (xy_q[:, 0] > 0) & (xy_q[:, 0] < qw)
            & (xy_q[:, 1] > 0) & (xy_q[:, 1] < qh)
        )
        X, xy_q, r_sel, c_sel = X[ok], xy_q[ok], r_sel[ok], c_sel[ok]
        matches = np.zeros((len(X), 5))
        matches[:, 0] = xy_q[:, 0] / qw
        matches[:, 1] = xy_q[:, 1] / qh
        matches[:, 2] = (c_sel + 1.2) / Wdb  # gathers back to (r,c) exactly
        matches[:, 3] = (r_sel + 1.2) / Hdb
        matches[:, 4] = 0.9
        # outliers above threshold + chaff below it
        n_out = 40
        junk = rng.random((n_out, 5))
        junk[:, 4] = 0.8
        low = rng.random((30, 5))
        low[:, 4] = 0.1  # must be dropped by the 0.75 threshold
        res = estimate_pose_from_matches(
            np.concatenate([matches, junk, low]),
            (qh, qw), xyzcut, T, focal,
            ransac_iters=800, seed=3,
        )
        dp, do = geometry.pose_distance(P_q, res.P)
        assert dp < 0.02 and np.rad2deg(do) < 0.2
        assert res.inliers.sum() >= len(X) * 0.9

    def test_run_pair_pnp_resume(self, rng, tmp_path):
        from ncnet_tpu.localization.pnp import pnp_artifact_path, run_pair_pnp

        xyzcut = rng.uniform(-1, 1, (8, 8, 3)) + np.array([0, 0, 5.0])
        args = dict(
            matches=np.zeros((4, 5)),  # nothing above threshold → NaN pose
            query_size=(100, 100),
            xyzcut=xyzcut,
            P_after=np.eye(4),
            focal=100.0,
        )
        P1, _ = run_pair_pnp(str(tmp_path), "q.jpg", "DUC1/DUC_cutout_001_0_0.jpg", **args)
        assert np.isnan(P1).all()
        art = pnp_artifact_path(str(tmp_path), "q.jpg", "DUC1/DUC_cutout_001_0_0.jpg")
        assert os.path.exists(art)
        mtime = os.path.getmtime(art)
        P2, _ = run_pair_pnp(str(tmp_path), "q.jpg", "DUC1/DUC_cutout_001_0_0.jpg", **args)
        assert os.path.getmtime(art) == mtime  # loaded, not recomputed
        np.testing.assert_array_equal(np.isnan(P1), np.isnan(P2))

    def test_artifact_paths_distinguish_floors(self, tmp_path):
        # same basename on two floors must map to two artifacts: the artifact
        # is the resume source of truth, so a collision silently reuses the
        # wrong floor's pose
        from ncnet_tpu.localization.pnp import pnp_artifact_path

        a = pnp_artifact_path(str(tmp_path), "q.jpg", "DUC1/DUC_cutout_024_30_0.jpg")
        b = pnp_artifact_path(str(tmp_path), "q.jpg", "DUC2/DUC_cutout_024_30_0.jpg")
        assert a != b
        assert os.path.dirname(a) == os.path.dirname(b)  # still flat per query

    def test_atomic_savemat(self, tmp_path, monkeypatch):
        from scipy.io import loadmat
        import scipy.io

        from ncnet_tpu.utils.io import atomic_savemat

        path = str(tmp_path / "out.mat")
        atomic_savemat(path, {"x": np.arange(3.0)})
        np.testing.assert_array_equal(
            loadmat(path)["x"].ravel(), np.arange(3.0)
        )
        assert not os.path.exists(path + ".tmp")

        # a crash mid-write must leave neither the target nor the temp file —
        # existence of the artifact is what resume trusts
        def boom(p, *a, **k):
            with open(p, "wb") as f:
                f.write(b"truncated")
            raise KeyboardInterrupt  # the kill-mid-write scenario

        monkeypatch.setattr(scipy.io, "savemat", boom)
        path2 = str(tmp_path / "crash.mat")
        with pytest.raises(KeyboardInterrupt):
            atomic_savemat(path2, {"x": np.arange(3.0)})
        assert not os.path.exists(path2)
        assert not os.path.exists(path2 + ".tmp")


class TestVerification:
    def test_true_pose_scores_higher(self, rng):
        from ncnet_tpu.localization.render import render_points_perspective
        from ncnet_tpu.localization.verification import verify_pose

        pts, rgb = make_plane_scene(rng, n=320, m=400)
        P_true = np.concatenate([np.eye(3), np.zeros((3, 1))], axis=1)
        qh, qw = 256, 320
        focal = 300.0
        K = geometry.intrinsics(focal, qh, qw)
        query, _ = render_points_perspective(rgb, pts, K @ P_true, qh, qw)
        ang = np.deg2rad(10)
        R = np.array(
            [
                [np.cos(ang), 0, np.sin(ang)],
                [0, 1, 0],
                [-np.sin(ang), 0, np.cos(ang)],
            ]
        )
        P_wrong = np.concatenate([R, np.array([[0.6], [0.0], [0.0]])], axis=1)
        s_true, _, _ = verify_pose(query, P_true, pts, rgb, focal)
        s_wrong, _, _ = verify_pose(query, P_wrong, pts, rgb, focal)
        s_nan, _, _ = verify_pose(query, np.full((3, 4), np.nan), pts, rgb, focal)
        assert s_true > s_wrong > 0
        assert s_nan == 0.0

    def test_group_by_scan(self):
        from ncnet_tpu.localization.verification import (
            PVItem,
            group_items_by_scan,
        )

        P = np.zeros((3, 4))
        items = [
            PVItem("q1", "DUC1/DUC_cutout_001_0_0.jpg", P),
            PVItem("q2", "DUC1/DUC_cutout_001_30_0.jpg", P),
            PVItem("q1", "DUC2/DUC_cutout_002_0_0.jpg", P),
        ]
        groups = group_items_by_scan(items)
        assert set(groups) == {"DUC1/DUC_001", "DUC2/DUC_002"}
        assert len(groups["DUC1/DUC_001"]) == 2


class TestComposition:
    @pytest.mark.slow
    def test_inloc_eval_feeds_localization(self, rng, tmp_path):
        """The L5→L6 boundary: matches written by ``run_inloc_eval`` must be
        directly consumable by ``run_localization`` (schema, folder naming,
        shortlist format, cutout-name parsing).  Pose quality is not asserted
        — the matcher here is a random tiny trunk; this test pins the
        composition contract the reference implements as .mat files handed to
        MATLAB."""
        import warnings

        from scipy.io import savemat

        from ncnet_tpu.config import EvalInLocConfig, LocalizationConfig
        from ncnet_tpu.config import ModelConfig
        from ncnet_tpu.data.synthetic import write_inloc_like
        from ncnet_tpu.evaluation.inloc import run_inloc_eval
        from ncnet_tpu.localization.driver import run_localization
        from ncnet_tpu.models import init_ncnet

        import jax

        root = str(tmp_path)
        shortlist = write_inloc_like(root, n_queries=1, n_panos=2,
                                     image_hw=(96, 128))
        model_config = ModelConfig(
            backbone="tiny", ncons_kernel_sizes=(3,), ncons_channels=(1,),
            half_precision=True, relocalization_k_size=2,
        )
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            params = init_ncnet(model_config, jax.random.key(0))
        eval_cfg = EvalInLocConfig(
            inloc_shortlist=shortlist, k_size=2, image_size=128,
            n_queries=1, n_panos=2,
            pano_path=os.path.join(root, "pano"),
            query_path=os.path.join(root, "query", "iphone7"),
            output_root=os.path.join(root, "matches"),
        )
        matches_dir = run_inloc_eval(eval_cfg, model_config=model_config,
                                     params=params, progress=False)

        # localization assets for the fixture's cutouts (both panos of query
        # 0 share scan id '000'): depth maps, transformation, scan, GT pose
        H, W = 96, 128
        gx, gy = np.meshgrid(np.arange(W), np.arange(H), indexing="xy")
        xyzcut = np.stack(
            [gx / 40.0, gy / 40.0, np.full((H, W), 5.0)], axis=2
        )
        for p in (0, 30):
            savemat(os.path.join(root, "pano", "DUC1",
                                 f"DUC_cutout_000_{p}_0.jpg.mat"),
                    {"XYZcut": xyzcut})
        os.makedirs(os.path.join(root, "DUC1", "transformations"))
        with open(os.path.join(root, "DUC1", "transformations",
                               "DUC_trans_000.txt"), "w") as f:
            f.write("synthetic\n")
            for row in np.eye(4):
                f.write(" ".join(str(v) for v in row) + "\n")
        pts = xyzcut.reshape(-1, 3)
        A = np.empty((1, 7), dtype=object)
        for i, col in enumerate(
            [pts[:, 0], pts[:, 1], pts[:, 2], np.ones(len(pts)),
             np.full(len(pts), 100.0), np.full(len(pts), 120.0),
             np.full(len(pts), 140.0)]
        ):
            A[0, i] = col.reshape(-1, 1)
        os.makedirs(os.path.join(root, "scans", "DUC1"))
        savemat(os.path.join(root, "scans", "DUC1", "DUC_scan_000.ptx.mat"),
                {"A": A})
        ref = np.empty((1,), dtype=[("queryname", object), ("P", object)])
        ref["queryname"][0] = "query_0.jpg"
        ref["P"][0] = np.concatenate([np.eye(3), np.zeros((3, 1))], axis=1)
        savemat(os.path.join(root, "refposes.mat"),
                {"DUC1_RefList": ref.reshape(1, -1),
                 "DUC2_RefList": ref[:0].reshape(1, -1)})

        loc_cfg = LocalizationConfig(
            matches_dir=matches_dir,
            shortlist=shortlist,
            query_path=os.path.join(root, "query", "iphone7"),
            cutout_path=os.path.join(root, "pano"),
            scan_path=os.path.join(root, "scans"),
            transformation_path=root,
            refposes=os.path.join(root, "refposes.mat"),
            output_dir=os.path.join(root, "out"),
            pnp_topN=2, ransac_iters=200, query_focal_length=100.0,
            match_score_thr=0.0,  # random-trunk scores are small
            progress=False,
        )
        curves = run_localization(loc_cfg)
        assert set(curves) == {"DensePE + NCNet", "InLoc + NCNet"}
        err_txt = os.path.join(root, "out", "error_DensePE + NCNet.txt")
        assert os.path.exists(err_txt)
        lines = open(err_txt).read().splitlines()
        assert len(lines) == 1 and lines[0].startswith("query_0.jpg ")


class TestDriver:
    @pytest.mark.slow
    @pytest.mark.parametrize("num_workers", [0, 2])
    def test_end_to_end_synthetic_scene(self, rng, tmp_path, num_workers):
        """Full L6 on a synthetic scene: shortlist + matches + depth maps +
        scans + transformations + GT poses on disk → PnP stage recovers the
        good candidate's pose, densePV reranks it to top-1, and the curves
        reflect it (PnP-only top-1 is the bad candidate by construction)."""
        from scipy.io import savemat

        from ncnet_tpu.config import LocalizationConfig
        from ncnet_tpu.localization.driver import run_localization
        from ncnet_tpu.localization.render import render_points_perspective

        root = tmp_path
        pts, rgb = make_plane_scene(rng, n=320, m=400)

        # scan-local coordinates differ from global by a rigid transform
        T = np.eye(4)
        ang = 0.4
        T[:3, :3] = np.array(
            [
                [np.cos(ang), -np.sin(ang), 0],
                [np.sin(ang), np.cos(ang), 0],
                [0, 0, 1],
            ]
        )
        T[:3, 3] = [0.3, -0.2, 0.1]
        T_inv = np.linalg.inv(T)
        pts_local = pts @ T_inv[:3, :3].T + T_inv[:3, 3]

        # --- assets on disk ---
        (root / "DUC1" / "transformations").mkdir(parents=True)
        with open(root / "DUC1" / "transformations" / "DUC_trans_001.txt", "w") as f:
            f.write("synthetic WUSTL transformation\n")
            for row in T:
                f.write(" ".join(f"{v:.10f}" for v in row) + "\n")
        A = np.empty((1, 7), dtype=object)
        for i, col in enumerate(
            [pts_local[:, 0], pts_local[:, 1], pts_local[:, 2],
             np.ones(len(pts_local)), rgb[:, 0], rgb[:, 1], rgb[:, 2]]
        ):
            A[0, i] = col.reshape(-1, 1)
        (root / "scans" / "DUC1").mkdir(parents=True)
        savemat(root / "scans" / "DUC1" / "DUC_scan_001.ptx.mat", {"A": A})

        # db cutout depth maps (local coords), one good + one decoy cutout
        Hdb, Wdb = 60, 80
        Kdb = geometry.intrinsics(70.0, Hdb, Wdb)
        P_db = np.concatenate([np.eye(3), np.zeros((3, 1))], axis=1)  # global
        inv = np.linalg.inv(Kdb)
        cols, rows = np.meshgrid(np.arange(Wdb), np.arange(Hdb), indexing="xy")
        pix = np.stack([cols, rows, np.ones_like(cols)], 2).astype(float)
        xyz_global = (pix @ inv.T) * 5.0  # db camera at origin, plane z=5
        xyz_local = (
            xyz_global.reshape(-1, 3) @ T_inv[:3, :3].T + T_inv[:3, 3]
        ).reshape(Hdb, Wdb, 3)
        cut_dir = root / "cutouts" / "DUC1"
        cut_dir.mkdir(parents=True)
        for name in ("DUC_cutout_001_0_0.jpg", "DUC_cutout_001_30_0.jpg"):
            savemat(cut_dir / (name + ".mat"), {"XYZcut": xyz_local})

        # query: rendered from the TRUE pose (so densePV can recognize it)
        P_q = np.concatenate(
            [np.eye(3), np.array([[0.15], [0.1], [0.2]])], axis=1
        )
        qh, qw = 256, 320
        focal = 300.0
        Kq = geometry.intrinsics(focal, qh, qw)
        query_img, _ = render_points_perspective(rgb, pts, Kq @ P_q, qh, qw)
        from PIL import Image

        (root / "query").mkdir()
        Image.fromarray(query_img).save(root / "query" / "q1.png")

        # shortlist: decoy cutout FIRST (PnP-only top-1 will be the decoy)
        good = "DUC1/DUC_cutout_001_0_0.jpg"
        decoy = "DUC1/DUC_cutout_001_30_0.jpg"
        imglist_entry = np.zeros((1, 1), dtype=object)
        entry = np.empty((1,), dtype=[("queryname", object), ("topNname", object)])
        entry["queryname"][0] = "q1.png"
        entry["topNname"][0] = np.array([decoy, good], dtype=object)
        imglist_entry[0, 0] = entry[0]
        savemat(root / "shortlist.mat", {"ImgList": entry.reshape(1, -1)})

        # matches: good cutout gets exact correspondences, decoy gets junk
        sel = rng.choice(Hdb * Wdb, size=150, replace=False)
        r_sel, c_sel = sel // Wdb, sel % Wdb
        X = xyz_global[r_sel, c_sel]
        xy_q, depth = geometry.project_points(P_q, Kq, X)
        ok = (
            (depth > 0)
            & (xy_q[:, 0] > 0) & (xy_q[:, 0] < qw)
            & (xy_q[:, 1] > 0) & (xy_q[:, 1] < qh)
        )
        X, xy_q, r_sel, c_sel = X[ok], xy_q[ok], r_sel[ok], c_sel[ok]
        good_rows = np.zeros((len(X), 5))
        good_rows[:, 0] = xy_q[:, 0] / qw
        good_rows[:, 1] = xy_q[:, 1] / qh
        good_rows[:, 2] = (c_sel + 1.2) / Wdb
        good_rows[:, 3] = (r_sel + 1.2) / Hdb
        good_rows[:, 4] = 0.9
        junk = rng.random((len(X), 5))
        junk[:, 4] = 0.9
        cap = len(X)
        match_table = np.zeros((1, 2, cap, 5))
        match_table[0, 0] = junk          # decoy is shortlist slot 0
        match_table[0, 1] = good_rows
        (root / "matches").mkdir()
        savemat(root / "matches" / "1.mat", {"matches": match_table})

        # ground truth: the query's true pose
        ref = np.empty((1,), dtype=[("queryname", object), ("P", object)])
        ref["queryname"][0] = "q1.png"
        ref["P"][0] = P_q
        savemat(
            root / "refposes.mat",
            {"DUC1_RefList": ref.reshape(1, -1),
             "DUC2_RefList": ref[:0].reshape(1, -1)},
        )

        config = LocalizationConfig(
            matches_dir=str(root / "matches"),
            shortlist=str(root / "shortlist.mat"),
            query_path=str(root / "query"),
            cutout_path=str(root / "cutouts"),
            cutout_mat_suffix=".mat",
            scan_path=str(root / "scans"),
            transformation_path=str(root),
            refposes=str(root / "refposes.mat"),
            output_dir=str(root / "out"),
            pnp_topN=2,
            ransac_iters=600,
            query_focal_length=focal,
            progress=False,
            num_workers=num_workers,  # 2 = the parfor-equivalent pool path
        )
        curves = run_localization(config)
        from ncnet_tpu.localization.curves import ERROR_THRESHOLDS

        i_half = np.abs(ERROR_THRESHOLDS - 0.5).argmin()
        # PnP-only follows shortlist order → decoy top-1 → not localized;
        # densePV reranks the good candidate first → localized well under 0.5m
        assert curves["DensePE + NCNet"][i_half] == pytest.approx(0.0)
        assert curves["InLoc + NCNet"][i_half] == pytest.approx(1.0)
        # artifacts exist: per-pair pnp .mat, ImgLists, curves + error txts
        # (names carry the non-default ransac_iters so reruns with other
        # settings cannot reload them)
        from ncnet_tpu.localization.driver import _pnp_matname, _pv_matname

        assert _pnp_matname(config) == "top_2_thr075_rthr020_it600.mat"
        assert (root / "out" / _pnp_matname(config)).exists()
        assert (root / "out" / _pv_matname(config)).exists()
        assert (root / "out" / "error_DensePE + NCNet.txt").exists()

        # resume: a second run must reload artifacts and reproduce the curves
        curves2 = run_localization(config)
        np.testing.assert_allclose(
            curves2["InLoc + NCNet"], curves["InLoc + NCNet"]
        )
