#!/usr/bin/env python
"""Entry shim: weak-supervision training (see ncnet_tpu/cli/train.py)."""
import sys

from ncnet_tpu.cli.train import main

if __name__ == "__main__":
    sys.exit(main())
