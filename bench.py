#!/usr/bin/env python
"""Benchmark: full NCNet forward (PF-Pascal config) on the available
accelerator, reported as ms/pair.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

``vs_baseline`` compares against a reference-style PyTorch CPU forward built
the way the reference builds it (NCHW ResNet-101 trunk, bmm correlation, 4D
convolution as a Python loop over F.conv3d — /root/reference/lib/conv4d.py:
39-48), at the same 400² / 25⁴ workload: value > 1 means this implementation
is faster.  The reference publishes no numbers of its own (BASELINE.md), so
the torch-CPU twin is the only baseline runnable in this image.
"""

import json
import time

BATCH = 4
IMAGE = 400
KERNELS = (5, 5, 5)
CHANNELS = (16, 16, 1)
ITERS = 10


def bench_tpu() -> float:
    """ms per pair for the jitted forward on jax's default backend."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ncnet_tpu.config import ModelConfig
    from ncnet_tpu import models

    cfg = ModelConfig(ncons_kernel_sizes=KERNELS, ncons_channels=CHANNELS)
    params = models.init_ncnet(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    src = jnp.asarray(rng.uniform(-1, 1, (BATCH, IMAGE, IMAGE, 3)).astype(np.float32))
    tgt = jnp.asarray(rng.uniform(-1, 1, (BATCH, IMAGE, IMAGE, 3)).astype(np.float32))

    fwd = jax.jit(lambda p, s, t: models.ncnet_forward(cfg, p, s, t).corr)
    fwd(params, src, tgt).block_until_ready()  # compile
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fwd(params, src, tgt)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    return dt / (ITERS * BATCH) * 1e3


def bench_torch_reference_style() -> float:
    """ms per pair for a reference-style torch CPU forward (random weights;
    timing only).  Mirrors the reference's structure, not its code: frozen
    NCHW ResNet-101[:layer3], bmm 4D correlation, mutual matching, and the
    conv4d-as-Python-loop-over-conv3d neighbourhood consensus."""
    import numpy as np
    import torch
    import torch.nn.functional as F

    torch.manual_seed(0)

    def conv_w(cout, cin, k):
        return torch.randn(cout, cin, k, k) * 0.05

    stages = {"layer1": (3, 64), "layer2": (4, 128), "layer3": (23, 256)}
    sd = {"conv1": conv_w(64, 3, 7)}
    inplanes = 64
    for s, (n, planes) in stages.items():
        for i in range(n):
            sd[f"{s}.{i}.c1"] = conv_w(planes, inplanes, 1)
            sd[f"{s}.{i}.c2"] = conv_w(planes, planes, 3)
            sd[f"{s}.{i}.c3"] = conv_w(planes * 4, planes, 1)
            if i == 0:
                sd[f"{s}.{i}.ds"] = conv_w(planes * 4, inplanes, 1)
                inplanes = planes * 4

    def backbone(x):
        x = F.relu(F.conv2d(x, sd["conv1"], stride=2, padding=3))
        x = F.max_pool2d(x, 3, 2, 1)
        for s, (n, _) in stages.items():
            for i in range(n):
                stride = 2 if (i == 0 and s != "layer1") else 1
                out = F.relu(F.conv2d(x, sd[f"{s}.{i}.c1"]))
                out = F.relu(F.conv2d(out, sd[f"{s}.{i}.c2"], stride=stride, padding=1))
                out = F.conv2d(out, sd[f"{s}.{i}.c3"])
                if f"{s}.{i}.ds" in sd:
                    x = F.conv2d(x, sd[f"{s}.{i}.ds"], stride=stride)
                x = F.relu(out + x)
        return F.normalize(x, dim=1)

    nc_w, nc_b = [], []
    cin = 1
    for k, cout in zip(KERNELS, CHANNELS):
        nc_w.append(torch.randn(cout, cin, k, k, k, k) * 0.05)
        nc_b.append(torch.zeros(cout))
        cin = cout

    def conv4d_loop(x, w, b):
        # the reference's structure: slice dim 2, conv3d per tap, accumulate
        bsz, cin_, ha, wa, hb, wb = x.shape
        cout, _, ka, kwa, kb, kwb = w.shape
        pad = ka // 2
        xp = F.pad(x, (0, 0, 0, 0, 0, 0, pad, pad))  # pad hA
        out = torch.zeros(bsz, cout, ha, wa, hb, wb)
        for i in range(ha):
            acc = None
            for p in range(ka):
                o = F.conv3d(xp[:, :, i + p], w[:, :, p], bias=None, padding=kwa // 2)
                acc = o if acc is None else acc + o
            out[:, :, i] = acc + b.view(1, -1, 1, 1, 1)
        return out

    def mutual(c):
        bsz, _, ha, wa, hb, wb = c.shape
        mb = c.view(bsz, ha * wa, hb, wb).max(1, keepdim=True)[0].view(bsz, 1, 1, 1, hb, wb)
        ma = c.view(bsz, ha, wa, hb * wb).max(3, keepdim=True)[0].view(bsz, 1, ha, wa, 1, 1)
        return c * (c / (mb + 1e-5)) * (c / (ma + 1e-5))

    x = torch.rand(1, 3, IMAGE, IMAGE)
    y = torch.rand(1, 3, IMAGE, IMAGE)
    with torch.no_grad():
        t0 = time.perf_counter()
        fa, fb = backbone(x), backbone(y)
        bsz, c, h, w = fa.shape
        corr = torch.bmm(
            fa.view(bsz, c, h * w).transpose(1, 2), fb.view(bsz, c, h * w)
        ).view(bsz, 1, h, w, h, w)
        corr = mutual(corr)
        v = corr
        for wgt, bias in zip(nc_w, nc_b):
            v = F.relu(conv4d_loop(v, wgt, bias))
        vt = v.permute(0, 1, 4, 5, 2, 3)
        # symmetric second pass
        v2 = corr.permute(0, 1, 4, 5, 2, 3)
        for wgt, bias in zip(nc_w, nc_b):
            v2 = F.relu(conv4d_loop(v2, wgt, bias))
        _ = mutual(v + v2.permute(0, 1, 4, 5, 2, 3))
        return (time.perf_counter() - t0) * 1e3


def main():
    ms_pair = bench_tpu()
    try:
        baseline_ms = bench_torch_reference_style()
        vs_baseline = baseline_ms / ms_pair
    except Exception:
        vs_baseline = 1.0
    print(
        json.dumps(
            {
                "metric": "pf_pascal_forward_ms_per_pair",
                "value": round(ms_pair, 3),
                "unit": "ms/pair",
                "vs_baseline": round(vs_baseline, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
