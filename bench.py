#!/usr/bin/env python
"""Benchmark: NCNet on the available accelerator at the PF-Pascal config.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N, "extra": {...}}

Headline metric: fp32 full-forward ms/pair at batch 4 (same workload as
round 1, for cross-round comparability).  ``extra`` carries the remaining
BASELINE.md north-stars — train pairs/sec and correlation-forward ms/pair —
plus the bf16 eval path and an MFU estimate from XLA's own FLOP count.

``vs_baseline`` compares a *matched batch-1* run against a reference-style
PyTorch CPU forward built the way the reference builds it (NCHW ResNet-101
trunk, bmm correlation, 4D convolution as a Python loop over F.conv3d —
/root/reference/lib/conv4d.py:39-48), both warmed up and averaged over
multiple iterations; > 1 means this implementation is faster.  The reference
publishes no numbers of its own (BASELINE.md), so the torch-CPU twin is the
only baseline runnable in this image.  When the baseline cannot run,
``vs_baseline`` is null.
"""

import json
import time

BATCH = 4
IMAGE = 400
KERNELS = (5, 5, 5)
CHANNELS = (16, 16, 1)
ITERS = 10

# bf16 peak TFLOP/s by device kind, for the MFU estimate (public specs)
_PEAK_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,   # v5e
    "TPU v5": 459.0,        # v5p
    "TPU v6 lite": 918.0,   # v6e (Trillium)
}


def _timeit(fn, args, iters=ITERS, per=1):
    import jax.numpy as jnp

    float(jnp.sum(fn(*args)))  # compile + settle
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    out.block_until_ready()
    return (time.perf_counter() - t0) / iters / per * 1e3


def bench_jax():
    """All JAX-side numbers on jax's default backend."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ncnet_tpu.config import ModelConfig, TrainConfig
    from ncnet_tpu import models, training
    from ncnet_tpu.models.ncnet import extract_features
    from ncnet_tpu.ops import correlation_4d

    cfg = ModelConfig(ncons_kernel_sizes=KERNELS, ncons_channels=CHANNELS)
    params = models.init_ncnet(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)

    def images(b):
        return (
            jnp.asarray(rng.uniform(-1, 1, (b, IMAGE, IMAGE, 3)).astype(np.float32)),
            jnp.asarray(rng.uniform(-1, 1, (b, IMAGE, IMAGE, 3)).astype(np.float32)),
        )

    src, tgt = images(BATCH)
    res = {}

    fwd = jax.jit(lambda p, s, t: models.ncnet_forward(cfg, p, s, t).corr)
    res["forward_ms_per_pair_fp32"] = _timeit(fwd, (params, src, tgt), per=BATCH)

    cfg16 = cfg.replace(half_precision=True, backbone_bf16=True)
    fwd16 = jax.jit(lambda p, s, t: models.ncnet_forward(cfg16, p, s, t).corr)
    res["forward_ms_per_pair_bf16"] = _timeit(fwd16, (params, src, tgt), per=BATCH)

    # MFU of the bf16 path from XLA's own FLOP count
    try:
        cost = fwd16.lower(params, src, tgt).compile().cost_analysis()
        flops = float(cost.get("flops", 0.0))
        kind = jax.devices()[0].device_kind
        peak = _PEAK_TFLOPS.get(kind)
        if flops > 0 and peak:
            tflops = flops / (res["forward_ms_per_pair_bf16"] * 1e-3 * BATCH) / 1e12
            res["forward_bf16_tflops"] = round(tflops, 2)
            res["forward_bf16_mfu_pct"] = round(100 * tflops / peak, 2)
            res["device_kind"] = kind
    except Exception:
        pass

    # correlation-only (BASELINE north-star: ms/pair 4D-corr fwd)
    feat = jax.jit(lambda p, x: extract_features(cfg, p, x))
    fa, fb = feat(params, src), feat(params, tgt)
    corr_fn = jax.jit(correlation_4d)
    res["corr_ms_per_pair"] = _timeit(corr_fn, (fa, fb), per=BATCH)

    # batch-1 forward for the matched-batch baseline comparison
    s1, t1 = images(1)
    res["forward_ms_per_pair_bs1"] = _timeit(fwd, (params, s1, t1), per=1)

    # train step (BASELINE north-star: image-pairs/sec; reference bs=16 —
    # on a single 16G chip the largest fitting batch is used and reported,
    # the full 16 sharding over ≥2 chips via the data mesh)
    for bs_try in (16, 8, 4):
        try:
            tcfg = TrainConfig(model=cfg, batch_size=bs_try, data_parallel=False)
            state, optimizer, mcfg, _ = training.create_train_state(tcfg)
            step = training.make_train_step(
                mcfg, optimizer, donate=False, stop_backbone_grad=True
            )
            bs_im, bt_im = images(bs_try)
            batch = {"source_image": bs_im, "target_image": bt_im}

            ms = _timeit(lambda b: step(state, b)[1], (batch,), iters=5)
            res["train_pairs_per_sec"] = bs_try / (ms * 1e-3)
            res["train_step_ms"] = ms
            res["train_batch_size"] = bs_try
            break
        except Exception as e:
            # expected path: OOM at bs16 on a single 16G chip → retry smaller.
            # Anything else is still printed so breakage can't hide as "didn't
            # fit" (stdout stays reserved for the one JSON line).
            import sys

            print(f"train bench bs={bs_try} failed: {str(e)[:200]}",
                  file=sys.stderr)
            continue
    return res


def bench_torch_reference_style(iters=3):
    """ms per pair, batch 1, for a reference-style torch CPU forward (random
    weights; timing only), with warm-up and averaging.  Mirrors the
    reference's structure, not its code: frozen NCHW ResNet-101[:layer3], bmm
    4D correlation, mutual matching, and the conv4d-as-Python-loop-over-conv3d
    neighbourhood consensus."""
    import torch
    import torch.nn.functional as F

    torch.manual_seed(0)

    def conv_w(cout, cin, k):
        return torch.randn(cout, cin, k, k) * 0.05

    stages = {"layer1": (3, 64), "layer2": (4, 128), "layer3": (23, 256)}
    sd = {"conv1": conv_w(64, 3, 7)}
    inplanes = 64
    for s, (n, planes) in stages.items():
        for i in range(n):
            sd[f"{s}.{i}.c1"] = conv_w(planes, inplanes, 1)
            sd[f"{s}.{i}.c2"] = conv_w(planes, planes, 3)
            sd[f"{s}.{i}.c3"] = conv_w(planes * 4, planes, 1)
            if i == 0:
                sd[f"{s}.{i}.ds"] = conv_w(planes * 4, inplanes, 1)
                inplanes = planes * 4

    def backbone(x):
        x = F.relu(F.conv2d(x, sd["conv1"], stride=2, padding=3))
        x = F.max_pool2d(x, 3, 2, 1)
        for s, (n, _) in stages.items():
            for i in range(n):
                stride = 2 if (i == 0 and s != "layer1") else 1
                out = F.relu(F.conv2d(x, sd[f"{s}.{i}.c1"]))
                out = F.relu(F.conv2d(out, sd[f"{s}.{i}.c2"], stride=stride, padding=1))
                out = F.conv2d(out, sd[f"{s}.{i}.c3"])
                if f"{s}.{i}.ds" in sd:
                    x = F.conv2d(x, sd[f"{s}.{i}.ds"], stride=stride)
                x = F.relu(out + x)
        return F.normalize(x, dim=1)

    nc_w, nc_b = [], []
    cin = 1
    for k, cout in zip(KERNELS, CHANNELS):
        nc_w.append(torch.randn(cout, cin, k, k, k, k) * 0.05)
        nc_b.append(torch.zeros(cout))
        cin = cout

    def conv4d_loop(x, w, b):
        # the reference's structure: slice dim 2, conv3d per tap, accumulate
        bsz, cin_, ha, wa, hb, wb = x.shape
        cout, _, ka, kwa, kb, kwb = w.shape
        pad = ka // 2
        xp = F.pad(x, (0, 0, 0, 0, 0, 0, pad, pad))  # pad hA
        out = torch.zeros(bsz, cout, ha, wa, hb, wb)
        for i in range(ha):
            acc = None
            for p in range(ka):
                o = F.conv3d(xp[:, :, i + p], w[:, :, p], bias=None, padding=kwa // 2)
                acc = o if acc is None else acc + o
            out[:, :, i] = acc + b.view(1, -1, 1, 1, 1)
        return out

    def mutual(c):
        bsz, _, ha, wa, hb, wb = c.shape
        mb = c.view(bsz, ha * wa, hb, wb).max(1, keepdim=True)[0].view(bsz, 1, 1, 1, hb, wb)
        ma = c.view(bsz, ha, wa, hb * wb).max(3, keepdim=True)[0].view(bsz, 1, ha, wa, 1, 1)
        return c * (c / (mb + 1e-5)) * (c / (ma + 1e-5))

    def forward():
        x = torch.rand(1, 3, IMAGE, IMAGE)
        y = torch.rand(1, 3, IMAGE, IMAGE)
        with torch.no_grad():
            fa, fb = backbone(x), backbone(y)
            bsz, c, h, w = fa.shape
            corr = torch.bmm(
                fa.view(bsz, c, h * w).transpose(1, 2), fb.view(bsz, c, h * w)
            ).view(bsz, 1, h, w, h, w)
            corr = mutual(corr)
            v = corr
            for wgt, bias in zip(nc_w, nc_b):
                v = F.relu(conv4d_loop(v, wgt, bias))
            v2 = corr.permute(0, 1, 4, 5, 2, 3)
            for wgt, bias in zip(nc_w, nc_b):
                v2 = F.relu(conv4d_loop(v2, wgt, bias))
            return mutual(v + v2.permute(0, 1, 4, 5, 2, 3))

    forward()  # warm-up
    t0 = time.perf_counter()
    for _ in range(iters):
        forward()
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    res = bench_jax()
    try:
        baseline_ms = bench_torch_reference_style()
        res["torch_cpu_ms_per_pair_bs1"] = round(baseline_ms, 1)
        vs_baseline = round(baseline_ms / res["forward_ms_per_pair_bs1"], 2)
    except Exception:
        vs_baseline = None
    print(
        json.dumps(
            {
                "metric": "pf_pascal_forward_ms_per_pair",
                "value": round(res.pop("forward_ms_per_pair_fp32"), 3),
                "unit": "ms/pair",
                "vs_baseline": vs_baseline,
                "extra": {k: round(v, 3) if isinstance(v, float) else v
                          for k, v in res.items()},
            }
        )
    )


if __name__ == "__main__":
    main()
