#!/usr/bin/env python
"""Benchmark: NCNet on the available accelerator at the PF-Pascal config.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N, "extra": {...}}

Headline metric: fp32 full-forward ms/pair at batch 4 (same workload as
round 1, for cross-round comparability).  ``extra`` carries the remaining
BASELINE.md north-stars — train pairs/sec and correlation-forward ms/pair —
plus the bf16 eval path and an MFU estimate from XLA's own FLOP count.

``vs_baseline`` compares a *matched batch-1* run against a reference-style
PyTorch CPU forward built the way the reference builds it (NCHW ResNet-101
trunk, bmm correlation, 4D convolution as a Python loop over F.conv3d —
/root/reference/lib/conv4d.py:39-48), both warmed up and averaged over
multiple iterations; > 1 means this implementation is faster.  The reference
publishes no numbers of its own (BASELINE.md), so the torch-CPU twin is the
only baseline runnable in this image.  When the baseline cannot run,
``vs_baseline`` is null.
"""

import json
import time

BATCH = 4
IMAGE = 400
KERNELS = (5, 5, 5)
CHANNELS = (16, 16, 1)

# device peaks live in ONE place — ncnet_tpu/observability/metrics.py — so
# the bench artifact and run telemetry can never disagree on the MFU /
# roofline denominators
from ncnet_tpu.observability.metrics import (  # noqa: E402
    PEAK_BF16_TFLOPS as _PEAK_TFLOPS,
    PEAK_HBM_GBPS as _PEAK_HBM_GBPS,
    filter_flops as _shared_filter_flops,
)


def _arch_filter_flops(feat_side: int) -> float:
    """True per-pair FLOPs of the SYMMETRIC NC filter at the bench arch
    (~281.2 GFLOP at the 25⁴ volume) — the constant algorithmic-MFU
    numerator shared by the roofline block and the train-step MFU
    (correlation + mutual matching are <1% each).  Delegates to the shared
    observability formula (metrics.filter_flops)."""
    return _shared_filter_flops(feat_side, KERNELS, CHANNELS)


def _timeit_scan(step_fn, make_input, per=1, n_long=6, reps=3):
    """Steady-state ms/iteration via scan-length differencing.

    The device tunnel in this rig both caches repeated identical executions
    and charges host→device upload to the first execution that touches a
    fresh buffer — a naive repeat-same-args loop measures either ~0 or the
    transfer, not the compute.  Instead: jit a program that generates its
    input ON DEVICE from a PRNG key and runs the op ``n`` times inside a
    ``lax.scan`` (serialized by a data dependence), then report
    ``(t[n_long] − t[1]) / (n_long − 1)`` with a fresh key per call so no
    call repeats.

    ``step_fn(x) -> x_next`` must keep the carry shape; ``make_input(key)``
    builds the initial carry on device.  Sub-ms ops need a long scan to rise
    above host-dispatch jitter — pick ``n_long`` so the long run spans ≥10ms.
    """
    from functools import partial

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    @partial(jax.jit, static_argnums=(1,))
    def run(key, n):
        def body(x, _):
            return step_fn(x), ()

        x, _ = lax.scan(body, make_input(key), None, length=n)
        return jnp.sum(jax.tree.leaves(x)[0].astype(jnp.float32))

    key = jax.random.key
    float(run(key(0), 1))
    float(run(key(1), n_long))  # compile both lengths
    diffs = []
    for i in range(reps):
        t0 = time.perf_counter()
        float(run(key(100 + i), 1))
        t1 = time.perf_counter()
        float(run(key(200 + i), n_long))
        t2 = time.perf_counter()
        diffs.append(((t2 - t1) - (t1 - t0)) / (n_long - 1) * 1e3)
    # a dispatch hiccup during a short run can push a diff negative; clamp
    # each rep so the median rejects corrupted samples instead of averaging
    # them in (reps should stay ≥3 for the median to actually reject one)
    return float(np.median([max(d, 0.0) for d in diffs])) / per


def _with_retries(fn, attempts=3, label=""):
    """Run a metric closure, retrying transient device-tunnel failures.

    The remote-compile service behind the tunneled TPU occasionally drops
    connections mid-compile (JaxRuntimeError: "response body closed...");
    one flaky metric must not zero the whole benchmark artifact.  Returns
    None when every attempt fails (callers emit the metrics they have).
    """
    import sys
    import time as _time

    for i in range(attempts):
        try:
            return fn()
        except Exception as e:
            print(f"bench metric {label or fn} attempt {i + 1}/{attempts} "
                  f"failed: {str(e)[:200]}", file=sys.stderr)
            if i < attempts - 1:  # no backoff after the final attempt
                _time.sleep(5 * (i + 1))
    return None


def bench_jax(res=None):
    """All JAX-side numbers on jax's default backend.

    Mutates (and returns) ``res`` so metrics collected before a mid-function
    failure survive for main()'s whole-run retry, which also skips metrics a
    previous attempt already captured.
    """
    import warnings

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ncnet_tpu.config import ModelConfig, TrainConfig
    from ncnet_tpu import models, training
    from ncnet_tpu.models.ncnet import extract_features
    from ncnet_tpu.ops import correlation_4d

    cfg = ModelConfig(ncons_kernel_sizes=KERNELS, ncons_channels=CHANNELS)

    def _init():
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # random-trunk warning: timing only
            return models.init_ncnet(cfg, jax.random.key(0))

    # init touches the device (key split + param upload): the same transient
    # tunnel failures the per-metric retries guard against can hit here too
    # (round-2 artifact died exactly at this line on an HTTP 500)
    params = _with_retries(_init, label="init_ncnet")
    if params is None:
        raise RuntimeError("init_ncnet failed after retries")
    res = {} if res is None else res

    def put(key, fn, label):
        """Measure into res[key] unless a prior attempt already did."""
        if res.get(key) is None:
            res[key] = _with_retries(fn, label=label)

    def image_pair_input(b):
        def make(key):
            k1, k2 = jax.random.split(key)
            return (
                jax.random.uniform(k1, (b, IMAGE, IMAGE, 3), jnp.float32, -1, 1),
                jax.random.uniform(k2, (b, IMAGE, IMAGE, 3), jnp.float32, -1, 1),
            )
        return make

    def chain_step(op):
        """Carry-preserving scan body: fold a negligible function of ``op``'s
        output back into the (a, b) carry so iterations form a data-dependent
        chain the compiler cannot collapse or the tunnel cache reuse."""
        def step(carry):
            a, b = carry
            out = op(a, b)
            eps = (jnp.sum(out.astype(jnp.float32)) * 1e-12).astype(a.dtype)
            return a + eps, b - eps
        return step

    def fwd_step(model_cfg):
        return chain_step(
            lambda src, tgt: models.ncnet_forward(model_cfg, params, src, tgt).corr
        )

    put(
        "forward_ms_per_pair_fp32",
        lambda: _timeit_scan(
            fwd_step(cfg), image_pair_input(BATCH), per=BATCH, n_long=12
        ),
        label="forward_fp32",
    )

    cfg16 = cfg.replace(half_precision=True, backbone_bf16=True)
    put(
        "forward_ms_per_pair_bf16",
        lambda: _timeit_scan(
            fwd_step(cfg16), image_pair_input(BATCH), per=BATCH, n_long=12
        ),
        label="forward_bf16",
    )

    # MFU of the bf16 path from XLA's own FLOP count — skipped entirely when
    # the bf16 timing failed (its lower+compile would be wasted work)
    if res.get("forward_ms_per_pair_bf16") is not None and \
            res.get("forward_bf16_mfu_pct") is None:
        try:
            rng = np.random.default_rng(0)
            src = jnp.asarray(
                rng.uniform(-1, 1, (BATCH, IMAGE, IMAGE, 3)).astype(np.float32)
            )
            fwd16 = jax.jit(
                lambda p, s, t: models.ncnet_forward(cfg16, p, s, t).corr
            )
            compiled16 = fwd16.lower(params, src, src).compile()
            cost = compiled16.cost_analysis()
            # memory ledger of the bench forward (observability/memory.py):
            # XLA's own accounting — temp bytes are the serving-relevant
            # per-program footprint, gated lower-is-better by perf_regress
            from ncnet_tpu.observability import memory as obs_memory

            mem16 = obs_memory.analysis_dict(compiled16)
            if mem16 and mem16.get("temp_bytes") is not None:
                res["mem_forward_temp_bytes"] = mem16["temp_bytes"]
                obs_memory.record_program(
                    "bench_forward",
                    f"{IMAGE}x{IMAGE}xb{BATCH}", analysis=compiled16,
                    tier="bf16", source="bench")
            flops = float(cost.get("flops", 0.0))
            kind = jax.devices()[0].device_kind
            peak = _PEAK_TFLOPS.get(kind)
            if flops > 0 and peak:
                tflops = (
                    flops / (res["forward_ms_per_pair_bf16"] * 1e-3 * BATCH)
                    / 1e12
                )
                res["forward_bf16_tflops"] = round(tflops, 2)
                res["forward_bf16_mfu_pct"] = round(100 * tflops / peak, 2)
                # EXECUTED-FLOPs MFU: numerator = XLA's cost analysis of the
                # compiled program, so it moves whenever a formulation
                # change cuts executed work (r4→r5 it DROPPED while the
                # forward got 1.8× faster).  Kept, explicitly named; the
                # cross-round-comparable companion (constant algorithmic
                # numerator) is emitted in the roofline block below.
                # Definitions: README "MFU accounting".  (VERDICT r5 #5)
                res["forward_bf16_mfu_executed_pct"] = \
                    res["forward_bf16_mfu_pct"]
                res["device_kind"] = kind
        except Exception:
            pass

    # match-quality signals of the synthetic bench pair, per precision tier
    # (observability/quality.py): every bench artifact records the tier
    # ladder's ACCURACY cost beside its walls — a kernel-tier PR that buys
    # speed by flattening the match distribution shows up here (and in the
    # perf store, where the quality_* series gate with direction inference)
    def _quality_for(model_cfg, suffix):
        def measure():
            from ncnet_tpu.observability.quality import (
                QUALITY_SIGNALS,
                active_tier,
                quality_table,
            )

            k1, k2 = jax.random.split(jax.random.key(7))
            src = jax.random.uniform(
                k1, (1, IMAGE, IMAGE, 3), jnp.float32, -1, 1)
            tgt = jax.random.uniform(
                k2, (1, IMAGE, IMAGE, 3), jnp.float32, -1, 1)
            table = np.asarray(jax.jit(
                lambda s, t: quality_table(
                    models.ncnet_forward(model_cfg, params, s, t).corr)
            )(src, tgt))
            vals = {f"quality_{name}_{suffix}": float(table[0, i])
                    for i, name in enumerate(QUALITY_SIGNALS)}
            # the tier the chooser actually picked for THIS forward — the
            # fp32 run never consults the chooser (it is xla by
            # construction) and must not inherit the bf16 timing runs'
            # process-global decision
            vals[f"quality_tier_{suffix}"] = active_tier(
                model_cfg.half_precision)
            return vals

        if res.get(f"quality_score_{suffix}") is None:
            out = _with_retries(measure, label=f"quality_{suffix}")
            if out:
                res.update(out)

    _quality_for(cfg, "fp32")
    _quality_for(cfg16, "bf16")

    # per-stage decomposition of the fused NC stack (ISSUE r6): time the
    # layout conversion and the layer prefixes of the SAME kernels the
    # production filter runs, so the residual roofline gap is attributed
    # (layout-in/out vs per-layer) instead of guessed.  Prefix chains carry
    # a wider-than-production final output (the probe relaxation), so the
    # last layer's delta slightly UNDERstates it vs the 16-channel prefix
    # write it is differenced against — noted in README.
    def _filter_stages():
        from ncnet_tpu.models.ncnet import extract_features as _ef
        from ncnet_tpu.ops.nc_fused_lane import (
            fused_layout_in,
            fused_layout_out,
            nc_stack_fused_lane,
            nc_stack_resident,
        )

        feat_shape = jax.eval_shape(
            lambda p, x: _ef(cfg16, p, x),
            params,
            jax.ShapeDtypeStruct((1, IMAGE, IMAGE, 3), jnp.float32),
        ).shape
        s = feat_shape[1]
        nv = 2 * BATCH  # symmetric batch-fold: 2 volumes per pair
        k = KERNELS[0]
        params16 = [
            jax.tree.map(lambda a: a.astype(jnp.bfloat16), layer)
            for layer in params["nc"]
        ]

        def vol_input(key):
            return (jax.random.normal(
                key, (nv, s, s, s, s, 1), jnp.bfloat16) * 0.1,)

        def eps_step(fn):
            def step(carry):
                (x,) = carry
                out = fn(x)
                return (x + (jnp.sum(out.astype(jnp.float32)) * 1e-12
                             ).astype(x.dtype),)
            return step

        # the chooser's own decision for this stack shape first: when an
        # ARITHMETIC tier (cp/fft, ISSUE 17) wins, decompose ITS layer
        # prefixes — the stage attribution must describe the implementation
        # production actually runs, not only the fused-lane tiers.  The
        # arithmetic chains consume the plain channels-last volume (no
        # layout conversion), so layer1 IS prefix1 there.
        from ncnet_tpu.ops import choose_fused_stack, cp_stack_ranks
        from ncnet_tpu.ops.conv4d_cp import nc_stack_cp
        from ncnet_tpu.ops.conv4d_fft import nc_stack_fft

        selected = choose_fused_stack(
            s, s, s, s, tuple(KERNELS), tuple(CHANNELS),
            cp_ranks=cp_stack_ranks(params16))
        if selected in ("cp", "fft"):
            afn = nc_stack_cp if selected == "cp" else nc_stack_fft
            stages = {"tier": selected}
            prev = None
            for n in range(1, len(params16) + 1):
                t = _with_retries(
                    lambda n=n: _timeit_scan(
                        eps_step(lambda x, n=n: afn(params16[:n], x)),
                        vol_input, per=BATCH, n_long=8),
                    label=f"filter_stage_prefix{n}",
                )
                if t is None:
                    return stages
                stages[f"stack_prefix{n}"] = t
                stages[f"layer{n}"] = t - (prev if prev is not None else 0.0)
                prev = t
            return stages

        stages = {}
        # layout-in and layout-out in isolation (cheap scalar-volume ops)
        stages["layout_in"] = _timeit_scan(
            eps_step(lambda x: fused_layout_in(x, k)), vol_input,
            per=BATCH, n_long=64)
        h = k - 1

        def out_input(key):
            return (jax.random.normal(
                key, (nv, s, s, 1, (s + h) * (s + h)), jnp.bfloat16) * 0.1,)

        stages["layout_out"] = _timeit_scan(
            eps_step(lambda o: fused_layout_out(o, s, s, k)), out_input,
            per=BATCH, n_long=64)
        # layer prefixes through the production fused stack.  ONE tier is
        # picked for every prefix — by compile-probing ALL prefix lengths of
        # the resident kernel first, else the per-layer chain — so the
        # per-layer deltas never difference timings of two different
        # implementations (Mosaic legality is shape-dependent, so a
        # per-prefix choice could mix tiers and emit negative/meaningless
        # attributions).  The chosen tier is recorded so readers know which
        # implementation the deltas describe.
        def tier_compiles(fn):
            try:
                for n in range(1, len(params16) + 1):
                    xs = jax.ShapeDtypeStruct(
                        (1, s, s, s, s, 1), jnp.bfloat16)
                    jax.jit(
                        lambda x, fn=fn, n=n: fn(
                            params16[:n], x, _allow_wide_final=True)
                    ).lower(xs).compile()
                return True
            except Exception:
                return False

        fn = next(
            (f for f in (nc_stack_resident, nc_stack_fused_lane)
             if tier_compiles(f)), None)
        if fn is None:
            return stages
        stages["tier"] = (
            "resident" if fn is nc_stack_resident else "perlayer")
        prev = None
        for n in range(1, len(params16) + 1):
            t = _with_retries(
                lambda n=n: _timeit_scan(
                    eps_step(lambda x, n=n: fn(
                        params16[:n], x, _allow_wide_final=True)),
                    vol_input, per=BATCH, n_long=8),
                label=f"filter_stage_prefix{n}",
            )
            if t is None:
                return stages  # keep whatever stages succeeded
            stages[f"stack_prefix{n}"] = t
            # layer1's delta subtracts the measured scalar-volume layout
            # conversion — exact for the resident tier; the per-layer
            # chain's own conversion also packs a _MIN_CB channel pad, so
            # there layer1 slightly overstates (noted via the tier field)
            stages[f"layer{n}"] = t - (
                prev if prev is not None
                else stages["layout_in"] + stages["layout_out"])
            prev = t
        return stages

    if res.get("filter_stage_layer1_ms") is None:
        st = _with_retries(_filter_stages, label="filter_stages") or {}
        for name, val in st.items():
            if name == "tier":
                res["filter_stage_tier"] = val
            else:
                res[f"filter_stage_{name}_ms"] = round(val, 4)

    # composed-forward roofline (VERDICT r3 item 6): measure the bf16 NC
    # FILTER stage alone (volume born from the production einsum), then set
    # it against analytic MXU and HBM lower bounds for the same stage
    def _filter_metric():
        feat_shape = jax.eval_shape(
            lambda p, x: extract_features(cfg16, p, x),
            params,
            jax.ShapeDtypeStruct((BATCH, IMAGE, IMAGE, 3), jnp.float32),
        ).shape
        from ncnet_tpu.models.ncnet import ncnet_filter
        from ncnet_tpu.ops import correlation_4d as corr4

        def filt_step(carry):
            fa, fb = carry
            corr = corr4(fa.astype(jnp.bfloat16), fb.astype(jnp.bfloat16))
            out = ncnet_filter(cfg16, params, corr).corr
            return (fa + (jnp.sum(out.astype(jnp.float32)) * 1e-12
                          ).astype(fa.dtype), fb)

        def filt_input(key):
            k1, k2 = jax.random.split(key)
            return (
                jax.random.normal(k1, feat_shape, jnp.float32) * 0.03,
                jax.random.normal(k2, feat_shape, jnp.float32) * 0.03,
            )

        return _timeit_scan(filt_step, filt_input, per=BATCH, n_long=8)

    put("filter_ms_per_pair_bf16", _filter_metric, label="filter_bf16")

    if res.get("filter_ms_per_pair_bf16") is not None and \
            res.get("roofline_verdict") is None:
        try:
            feat_shape = jax.eval_shape(
                lambda p, x: extract_features(cfg, p, x),
                params,
                jax.ShapeDtypeStruct((1, IMAGE, IMAGE, 3), jnp.float32),
            ).shape
            cells = (feat_shape[1] * feat_shape[2]) ** 2  # 25^4 volume
            sym = 2
            chans = list(zip((1,) + CHANNELS[:-1], CHANNELS))
            flops = _arch_filter_flops(feat_shape[1])
            # bf16 bytes: algorithmic minimum = each layer reads/writes the
            # whole volume at its channel widths, + 2 mutual-matching passes
            bpv = 2 * cells  # bytes per 1-channel bf16 volume
            algo_bytes = sym * sum(
                bpv * (ci + co) for _, (ci, co) in zip(KERNELS, chans)
            ) + 4 * 2 * bpv
            # as-formulated adds the channel-folding intermediates the
            # measured-fastest formulations materialize (ops/conv4d.py:
            # tapfold kA*ci input fold, coutfold kA*co output fold, w+r each)
            form_bytes = algo_bytes + sym * sum(
                2 * bpv * (k * (ci if ci <= 4 else co))
                for k, (ci, co) in zip(KERNELS, chans)
            )
            kind = jax.devices()[0].device_kind
            peak_f = _PEAK_TFLOPS.get(kind)
            peak_b = _PEAK_HBM_GBPS.get(kind)
            if peak_f and peak_b:
                mxu_ms = flops / (peak_f * 1e12) * 1e3
                hbm_ms = form_bytes / (peak_b * 1e9) * 1e3
                meas = res["filter_ms_per_pair_bf16"]
                res["roofline_filter_gflops_per_pair"] = round(flops / 1e9, 1)
                res["roofline_filter_mxu_bound_ms"] = round(mxu_ms, 3)
                res["roofline_filter_hbm_bound_ms"] = round(hbm_ms, 3)
                res["roofline_filter_hbm_algorithmic_ms"] = round(
                    algo_bytes / (peak_b * 1e9) * 1e3, 3)
                res["roofline_filter_pct_of_mxu_bound"] = round(
                    100 * mxu_ms / meas, 1)
                # ALGORITHMIC-FLOPs MFU (VERDICT r5 #5): constant numerator
                # = the true NC-stack FLOPs of the fixed bench arch
                # (~281.2 GFLOP/pair, the `flops` above), so these numbers
                # compare across rounds no matter how the lowering
                # reformulates the executed work.  filter_…_algorithmic is
                # arithmetically identical to roofline_filter_pct_of_
                # mxu_bound (same ratio, MFU-named); forward_…_algorithmic
                # uses the same numerator over the whole forward.
                res["filter_bf16_mfu_algorithmic_pct"] = round(
                    100 * (flops / (meas * 1e-3) / 1e12) / peak_f, 2)
                if res.get("forward_ms_per_pair_bf16"):
                    res["forward_bf16_mfu_algorithmic_pct"] = round(
                        100 * (flops / (res["forward_ms_per_pair_bf16"]
                                        * 1e-3) / 1e12) / peak_f, 2)
                # the binding constraint is whichever analytic bound is
                # larger.  On v5e the MXU bound (1.43 ms) exceeds the HBM
                # bound (0.48 ms as-formulated) — the filter is NOT
                # bandwidth-bound.  r4 measured ~7.9 ms (18% of the MXU
                # bound): XLA's conv lowering of the 4D-decomposed shapes.
                # r5: ~4.5 ms (~32%) with the per-layer fused-(hB·wB)-lane
                # Pallas chain.  r6 attacks the remainder with the RESIDENT
                # whole-stack kernel (nc_stack_resident: intermediates in
                # VMEM rings — no inter-layer HBM round trips or k× row
                # refetch — and exact thin-layer K/N widths, ~20% fewer
                # executed dot FLOPs); the filter_stage_* extras above
                # attribute whatever gap remains (layout vs per-layer)
                res["roofline_verdict"] = (
                    "mxu-lowering-bound" if mxu_ms >= hbm_ms else "hbm-bound"
                )
        except Exception:
            pass

    # memory ledger of the bf16 FILTER stage alone (one AOT analysis
    # compile; the measured twin of the roofline's accounted bytes): temp
    # bytes here are the 4D-volume working set items 2-3 of the roadmap
    # promise to shrink — the series their PRs will gate against
    def _filter_memory():
        from ncnet_tpu.models.ncnet import ncnet_filter
        from ncnet_tpu.observability import memory as obs_memory
        from ncnet_tpu.ops import correlation_4d as corr4

        feat_shape = jax.eval_shape(
            lambda p, x: extract_features(cfg16, p, x),
            params,
            jax.ShapeDtypeStruct((BATCH, IMAGE, IMAGE, 3), jnp.float32),
        ).shape

        def filt(p, fa, fb):
            corr = corr4(fa.astype(jnp.bfloat16), fb.astype(jnp.bfloat16))
            return ncnet_filter(cfg16, p, corr).corr

        sds = jax.ShapeDtypeStruct(feat_shape, jnp.float32)
        compiled_f = jax.jit(filt).lower(params, sds, sds).compile()
        mem_f = obs_memory.analysis_dict(compiled_f)
        if not mem_f or mem_f.get("temp_bytes") is None:
            return None
        obs_memory.record_program(
            "bench_filter", f"{feat_shape[1]}x{feat_shape[2]}xb{BATCH}",
            analysis=compiled_f, tier="bf16", source="bench")
        return mem_f["temp_bytes"]

    # the AOT compile behind this rides the same flaky remote-compile
    # tunnel as every other metric: retried, never silently dropped
    put("mem_filter_temp_bytes", _filter_memory, label="mem_filter")

    # ------------------------------------------------------------------
    # high-resolution coarse-to-fine scenario (ISSUE 15): the 2× feature
    # grid the dense volume prices out of — sparse (coarse2fine, k=4) vs
    # dense filter walls and ledger-measured temp footprints at the SAME
    # shape.  All four series are perf-store-ingested (name tokens `_ms` /
    # `_bytes` gate lower-is-better), so both the speed and the memory
    # claim ride `perf_regress --check`.  TPU-gated like the PF eval wall
    # (a 50⁴ dense volume on a CPU backend is minutes per iteration);
    # NCNET_BENCH_SPARSE=1 forces, =0 skips.
    # ------------------------------------------------------------------
    _SPARSE_K = 4

    def _sparse_gate():
        import os as _os

        flag = _os.environ.get("NCNET_BENCH_SPARSE")
        on_tpu_ = "TPU" in jax.devices()[0].device_kind
        return flag not in ("0", "") if flag is not None else on_tpu_

    def _sparse_shapes():
        feat_shape = jax.eval_shape(
            lambda p, x: extract_features(cfg16, p, x),
            params,
            jax.ShapeDtypeStruct((1, IMAGE, IMAGE, 3), jnp.float32),
        ).shape
        return 2 * feat_shape[1], feat_shape[3]  # 2× side, channels

    cfg_sp = cfg16.replace(sparse_topk=_SPARSE_K)

    def _highres_input(s2, cdim):
        def make(key):
            k1, k2 = jax.random.split(key)
            return (
                jax.random.normal(k1, (1, s2, s2, cdim), jnp.float32) * 0.05,
                jax.random.normal(k2, (1, s2, s2, cdim), jnp.float32) * 0.05,
            )
        return make

    if _sparse_gate():
        from ncnet_tpu.models.ncnet import coarse2fine_filter, ncnet_filter
        from ncnet_tpu.ops import (
            correlation_4d as _corr4,
            pool_features,
            topk_candidates,
        )

        s2, cdim = _sparse_shapes()

        # coarse select stage alone: pool → coarse corr → coarse filter →
        # per-row top-k (the candidate-selection overhead the fine stage's
        # savings must beat)
        def _topk_select(fa, fb):
            fac = pool_features(fa.astype(jnp.bfloat16), cfg_sp.sparse_factor)
            fbc = pool_features(fb.astype(jnp.bfloat16), cfg_sp.sparse_factor)
            coarse = ncnet_filter(cfg16, params, _corr4(fac, fbc)).corr
            return topk_candidates(coarse, _SPARSE_K).astype(jnp.float32)

        put(
            "topk_select_ms",
            lambda: _timeit_scan(
                chain_step(_topk_select), _highres_input(s2, cdim),
                per=1, n_long=8),
            label="topk_select",
        )

        # the full coarse-to-fine filter (coarse pass + selection + gathered
        # fine refinement + scatter) — the sparse stand-in for the dense
        # filter stage at 2× resolution
        put(
            "sparse_fine_wall_ms",
            lambda: _timeit_scan(
                chain_step(
                    lambda fa, fb: coarse2fine_filter(
                        cfg_sp, params, fa, fb).corr),
                _highres_input(s2, cdim), per=1, n_long=6),
            label="sparse_fine",
        )

        # dense at the SAME 2× shape: may OOM/fail where sparse runs —
        # exactly the headline; a missing value here IS the result then
        put(
            "filter_wall_ms_dense_highres",
            lambda: _timeit_scan(
                chain_step(
                    lambda fa, fb: ncnet_filter(
                        cfg16, params,
                        _corr4(fa.astype(jnp.bfloat16),
                               fb.astype(jnp.bfloat16))).corr),
                _highres_input(s2, cdim), per=1, n_long=6),
            label="filter_dense_highres",
        )
        if res.get("sparse_fine_wall_ms") is not None \
                and res.get("filter_wall_ms_dense_highres") \
                and res.get("filter_wall_ms_sparse_vs_dense") is None:
            res["filter_wall_ms_sparse_vs_dense"] = round(
                res["sparse_fine_wall_ms"]
                / res["filter_wall_ms_dense_highres"], 4)

        # ledger-measured temp footprints of both programs at the 2× shape
        # (observability/memory.py): THE memory claim of ROADMAP item 2,
        # gated lower-is-better by perf_regress
        def _sparse_memory(fn, program, tier):
            from ncnet_tpu.observability import memory as obs_memory

            sds = jax.ShapeDtypeStruct((1, s2, s2, cdim), jnp.float32)
            compiled = jax.jit(fn).lower(params, sds, sds).compile()
            mem = obs_memory.analysis_dict(compiled)
            if not mem or mem.get("temp_bytes") is None:
                return None
            obs_memory.record_program(
                program, f"{s2}x{s2}x{cdim}xb1|k={_SPARSE_K}",
                analysis=compiled, tier=tier, source="bench")
            return mem["temp_bytes"]

        put(
            "mem_filter_temp_bytes_sparse",
            lambda: _sparse_memory(
                lambda p, fa, fb: coarse2fine_filter(cfg_sp, p, fa, fb).corr,
                "bench_sparse_filter", "coarse2fine"),
            label="mem_sparse_filter",
        )
        put(
            "mem_filter_temp_bytes_dense_highres",
            lambda: _sparse_memory(
                lambda p, fa, fb: ncnet_filter(
                    cfg16, p, _corr4(fa.astype(jnp.bfloat16),
                                     fb.astype(jnp.bfloat16))).corr,
                "bench_filter_highres", "bf16"),
            label="mem_dense_filter_highres",
        )

    # ------------------------------------------------------------------
    # arithmetic-tier scenario (ISSUE 17): the CP / FFT conv4d tiers at the
    # production stack shape — forced-tier filter walls, the CP chain's
    # ledger temp bytes, and the default rank's label-free PCK-recovery
    # proxy (argmax-match agreement vs the dense filter).  Name tokens
    # perf-store-gate them (`_ms`/`_bytes` lower, `recovery_pct` higher).
    # TPU-gated like the sparse scenario; NCNET_BENCH_ARITH=1 forces,
    # =0 skips.
    # ------------------------------------------------------------------
    def _arith_gate():
        import os as _os

        flag = _os.environ.get("NCNET_BENCH_ARITH")
        on_tpu_ = "TPU" in jax.devices()[0].device_kind
        return flag not in ("0", "") if flag is not None else on_tpu_

    if _arith_gate():
        from ncnet_tpu.models.ncnet import ncnet_filter as _ncf
        from ncnet_tpu.ops import correlation_4d as _c4
        from ncnet_tpu.ops.conv4d_cp import DEFAULT_CP_RANK as _CP_R
        from ncnet_tpu.ops.cp_als import decompose_stack as _cp_dec

        feat_shape = jax.eval_shape(
            lambda p, x: extract_features(cfg16, p, x),
            params,
            jax.ShapeDtypeStruct((BATCH, IMAGE, IMAGE, 3), jnp.float32),
        ).shape
        params_cp = dict(params)
        params_cp["nc"], _cp_errs = _cp_dec(params["nc"], _CP_R)

        def _arith_input(key):
            k1, k2 = jax.random.split(key)
            return (
                jax.random.normal(k1, feat_shape, jnp.float32) * 0.03,
                jax.random.normal(k2, feat_shape, jnp.float32) * 0.03,
            )

        def _tier_wall(cfg_t, p):
            def step(carry):
                fa, fb = carry
                corr = _c4(fa.astype(jnp.bfloat16), fb.astype(jnp.bfloat16))
                out = _ncf(cfg_t, p, corr).corr
                return (fa + (jnp.sum(out.astype(jnp.float32)) * 1e-12
                              ).astype(fa.dtype), fb)

            return _timeit_scan(step, _arith_input, per=BATCH, n_long=8)

        put(
            f"filter_wall_ms_cp_r{_CP_R}",
            lambda: _tier_wall(cfg16.replace(nc_tier="cp"), params_cp),
            label="filter_cp",
        )
        put(
            "filter_wall_ms_fft",
            lambda: _tier_wall(cfg16.replace(nc_tier="fft"), params),
            label="filter_fft",
        )

        def _cp_memory():
            from ncnet_tpu.observability import memory as obs_memory

            cfg_cp = cfg16.replace(nc_tier="cp")

            def filt(p, fa, fb):
                corr = _c4(fa.astype(jnp.bfloat16),
                           fb.astype(jnp.bfloat16))
                return _ncf(cfg_cp, p, corr).corr

            sds = jax.ShapeDtypeStruct(feat_shape, jnp.float32)
            compiled = jax.jit(filt).lower(params_cp, sds, sds).compile()
            mem = obs_memory.analysis_dict(compiled)
            if not mem or mem.get("temp_bytes") is None:
                return None
            obs_memory.record_program(
                "bench_filter_cp",
                f"{feat_shape[1]}x{feat_shape[2]}xb{BATCH}|r={_CP_R}",
                analysis=compiled, tier="cp", source="bench")
            return mem["temp_bytes"]

        put("mem_filter_temp_bytes_cp", _cp_memory, label="mem_filter_cp")

        def _cp_recovery():
            # label-free PCK proxy: the fraction of target cells whose
            # argmax source match survives the rank-R factorization —
            # computed fp32 on one deterministic synthetic pair, the same
            # quantity fine-tuning is asked to recover (ISSUE 17)
            k1, k2 = jax.random.split(jax.random.key(7))
            fa = jax.random.normal(k1, feat_shape, jnp.float32) * 0.03
            fb = jax.random.normal(k2, feat_shape, jnp.float32) * 0.03
            vd = jax.jit(
                lambda p, a, b_: _ncf(cfg, p, _c4(a, b_)).corr
            )(params, fa, fb)
            vc = jax.jit(
                lambda p, a, b_: _ncf(
                    cfg.replace(nc_tier="cp"), p, _c4(a, b_)).corr
            )(params_cp, fa, fb)
            b, ha, wa, hb, wb = vd.shape
            bd = jnp.argmax(vd.reshape(b, ha * wa, hb * wb), axis=1)
            bc = jnp.argmax(vc.reshape(b, ha * wa, hb * wb), axis=1)
            return round(
                float(jnp.mean((bd == bc).astype(jnp.float32))) * 100, 2)

        put("cp_rank_pck_recovery_pct", _cp_recovery, label="cp_recovery")

    # correlation-only (BASELINE north-star: ms/pair 4D-corr fwd) — feature
    # shape derived from the configured backbone via eval_shape (free), so a
    # config change cannot silently decouple this metric from the model
    def _corr_metric():
        feat_shape = jax.eval_shape(
            lambda p, x: extract_features(cfg, p, x),
            params,
            jax.ShapeDtypeStruct((BATCH, IMAGE, IMAGE, 3), jnp.float32),
        ).shape

        corr_step = chain_step(correlation_4d)

        def corr_input(key):
            k1, k2 = jax.random.split(key)
            return (
                jax.random.normal(k1, feat_shape, jnp.float32) * 0.03,
                jax.random.normal(k2, feat_shape, jnp.float32) * 0.03,
            )

        # the einsum correlation is ~0.1ms/batch where the tunnel's dispatch
        # jitter is ±40ms: scan 2048 deep so compute dominates the span
        return _timeit_scan(corr_step, corr_input, per=BATCH, n_long=2048)

    put("corr_ms_per_pair", _corr_metric, label="corr")

    # batch-1 forward for the matched-batch baseline comparison.  The
    # scan-differenced number IS device time: host dispatch and transfers
    # are identical between the short and long scans and cancel in the
    # difference — recorded under both names (VERDICT r4 item 3 asked for
    # the device/wall separation explicitly).
    put(
        "forward_ms_per_pair_bs1",
        lambda: _timeit_scan(
            fwd_step(cfg), image_pair_input(1), per=1, n_long=24
        ),
        label="forward_bs1",
    )
    if res.get("forward_ms_per_pair_bs1") is not None:
        res["forward_device_ms_per_pair_bs1"] = res["forward_ms_per_pair_bs1"]

    # single-dispatch WALL at bs1: what a serial caller actually waits
    # through the tunnel per pair.  Since r6 this measures the DEMO PATH
    # (models/ncnet.py make_point_matcher): persistent warm program with
    # pre-staged weights, raw uint8 upload (~1 MB/pair vs 3.8 fp32),
    # device-side normalization, and the compact corr_to_matches table
    # downloaded (~15 KB) instead of the fp32 volume (~1.6 MB) — the same
    # fp32 model config as the device-time basis it is compared against.
    # The old full-volume wall stays as …_fullcorr for cross-round
    # comparability (r5: 681 ms against 15.4 ms of device time).
    def _bs1_wall():
        from ncnet_tpu.models import make_point_matcher

        matcher = make_point_matcher(cfg, params, do_softmax=True)
        rng = np.random.default_rng(3)

        def fresh_pair():
            return (rng.integers(0, 255, (1, IMAGE, IMAGE, 3), dtype=np.uint8),
                    rng.integers(0, 255, (1, IMAGE, IMAGE, 3), dtype=np.uint8))

        matcher(*fresh_pair())  # compile + weight staging
        walls = []
        for _ in range(5):
            s, t = fresh_pair()
            t0 = time.perf_counter()
            matcher(s, t)
            walls.append((time.perf_counter() - t0) * 1e3)
        return float(np.median(walls))

    put("forward_wall_ms_per_pair_bs1", _bs1_wall, label="forward_bs1_wall")

    def _bs1_wall_fullcorr():
        fwd1 = jax.jit(
            lambda p, s, t: models.ncnet_forward(cfg, p, s, t).corr
        )
        rng = np.random.default_rng(3)

        def fresh_pair():
            return (jnp.asarray(rng.uniform(-1, 1, (1, IMAGE, IMAGE, 3))
                                .astype(np.float32)),
                    jnp.asarray(rng.uniform(-1, 1, (1, IMAGE, IMAGE, 3))
                                .astype(np.float32)))

        np.asarray(fwd1(params, *fresh_pair()))  # compile
        walls = []
        for _ in range(5):
            s, t = fresh_pair()
            t0 = time.perf_counter()
            np.asarray(fwd1(params, s, t))
            walls.append((time.perf_counter() - t0) * 1e3)
        return float(np.median(walls))

    put("forward_wall_ms_per_pair_bs1_fullcorr", _bs1_wall_fullcorr,
        label="forward_bs1_wall_fullcorr")

    # bs1 on the bf16 path: the fused-lane filter's per-volume cost is
    # batch-independent, so the fp32 bs1 penalty (the fp32 filter at conv
    # batch 2 underfilling the MXU — r5 attribution: filter 13.6 ms/pair at
    # bs1 vs 10.6 at bs4, trunk+corr 1.7 vs 1.1) vanishes here
    put(
        "forward_device_ms_per_pair_bs1_bf16",
        lambda: _timeit_scan(
            fwd_step(cfg16), image_pair_input(1), per=1, n_long=24
        ),
        label="forward_bs1_bf16",
    )

    # full PF-Pascal test-split eval wall (VERDICT r4 item 7): the one
    # reference workload not previously timed end-to-end.  299 pairs (the
    # real test_pairs.csv size) through the production run_eval — IO,
    # decode, resize, batching, bf16 forward, match extraction, PCK — on
    # synthetic JPEGs at the real layout (the dataset images cannot be
    # vendored; zero egress).  One warm pass absorbs compiles, the second
    # is the reported wall.  Reference regime: eval_pf_pascal.py:69-89,
    # bs1-only; this path batches 16.
    def _pf_eval_total():
        import os as _os
        import shutil
        import tempfile

        # same gate as the InLoc metric below: 2x299 forwards at image 400
        # are an hour-plus on a CPU backend; TPU by default, env-forceable
        flag = _os.environ.get("NCNET_BENCH_PF_EVAL")
        on_tpu_ = "TPU" in jax.devices()[0].device_kind
        if not (flag not in ("0", "") if flag is not None else on_tpu_):
            return None

        from ncnet_tpu.config import EvalPFPascalConfig
        from ncnet_tpu.data.synthetic import write_pf_pascal_like
        from ncnet_tpu.evaluation.pf_pascal import run_eval
        from ncnet_tpu.models import NCNet

        root = tempfile.mkdtemp(prefix="bench_pf_")
        try:
            write_pf_pascal_like(root, n_pairs=299, image_hw=(IMAGE, IMAGE))
            ecfg = EvalPFPascalConfig(eval_dataset_path=root,
                                      image_size=IMAGE)
            net = NCNet(cfg16, params=params)
            kw = dict(batch_size=16, num_workers=4, progress=False)
            run_eval(ecfg, net=net, **kw)  # warm: compiles charged here
            t0 = time.perf_counter()
            out = run_eval(ecfg, net=net, **kw)
            dt = time.perf_counter() - t0
            if out["total"] != 299:
                raise RuntimeError(f"eval saw {out['total']} pairs, not 299")
            # wall attribution (VERDICT r5 #2): decode = waiting on the
            # loader, dispatch = upload + async enqueue, fetch = blocking
            # result pulls; the residual is host-side collation/python.
            # Device-time estimate from the scan-differenced bf16 forward.
            for key, val in out["timing"].items():
                res[f"pf_pascal_eval_s_{key.removesuffix('_s')}"] = round(
                    val, 2)
            if res.get("forward_ms_per_pair_bf16"):
                res["pf_pascal_eval_s_device_est"] = round(
                    res["forward_ms_per_pair_bf16"] * out["total"] / 1e3, 2)
            return round(dt, 2)
        finally:
            shutil.rmtree(root, ignore_errors=True)

    put("pf_pascal_eval_s_total", _pf_eval_total, label="pf_eval_total")

    # InLoc-resolution matcher (56M-cell pooled volume, k=2, IVD arch) —
    # default-on since round 3 on TPU devices (the depth-2 dispatch pipeline
    # is a headline metric); NCNET_BENCH_INLOC=0 / empty skips its ~1 min
    # compile+run, and non-TPU backends skip it unless explicitly forced
    # (the 56M-cell bf16 forward is minutes-to-OOM territory on CPU)
    import os

    flag = os.environ.get("NCNET_BENCH_INLOC")
    on_tpu = "TPU" in jax.devices()[0].device_kind
    if (flag not in ("0", "") if flag is not None else on_tpu):

        def inloc_with_percentiles():
            mean_s, p50, p95 = _bench_inloc_matcher()
            # per-pair latency spread (VERDICT r3 item 5): the tunnel's
            # dispatch latency varies ~2-3x run to run, so the README quotes
            # a band and the bench records where in it this run landed
            res["inloc_matcher_s_per_pair_p50"] = p50
            res["inloc_matcher_s_per_pair_p95"] = p95
            return mean_s

        put("inloc_matcher_s_per_pair", inloc_with_percentiles,
            label="inloc_matcher")

    # cached-localization scenario (ISSUE 14, ncnet_tpu/store/): one full
    # 10-pano InLoc query against a COLD feature store (pano features
    # computed + committed) vs the same query WARM (verified store hits,
    # zero database-side extractions), plus the store's deterministic hit
    # fraction over the scenario.  All three land in the perf store —
    # *_query_ms with the inferred lower-is-better direction and
    # store_hit_pct via the higher-is-better hit_pct token — so
    # perf_regress --check gates the cache win like every other wall.
    # Same gate as the InLoc matcher (the 56M-cell volume is CPU-hostile);
    # NCNET_BENCH_STORE=1 forces it elsewhere.
    flag = os.environ.get("NCNET_BENCH_STORE")
    on_tpu = "TPU" in jax.devices()[0].device_kind
    if (flag not in ("0", "") if flag is not None else on_tpu) \
            and res.get("inloc_cached_query_ms") is None:

        def _store_cached_metrics():
            out = {}
            cold_s, warm_s, hit_pct = _bench_store_cached_query()
            out["inloc_cold_query_ms"] = round(cold_s * 1e3, 2)
            out["inloc_cached_query_ms"] = round(warm_s * 1e3, 2)
            out["store_hit_pct"] = hit_pct
            return out

        out = _with_retries(_store_cached_metrics, label="store_cached") \
            or {}
        res.update(out)

    # resident match SERVICE under offered load (ISSUE r8): open-loop sweep
    # against ncnet_tpu/serving at the bench arch — capacity (closed loop),
    # steady-state latency percentiles at 70% of capacity (open loop, so
    # queueing delay is measured, not hidden by client backpressure), and
    # the shed fraction under a pinned ~3x-capacity burst.  The serve_*
    # series land in the perf store with inferred directions (qps higher,
    # *_ms lower, shed_pct lower), so perf_regress --check gates serving
    # latency like every other metric.  TPU-gated like the InLoc metric;
    # NCNET_BENCH_SERVE=1 forces it elsewhere.
    flag = os.environ.get("NCNET_BENCH_SERVE")
    on_tpu = "TPU" in jax.devices()[0].device_kind
    if (flag not in ("0", "") if flag is not None else on_tpu) \
            and res.get("serve_qps") is None:

        def _serving_metrics():
            import itertools

            from ncnet_tpu.serving import MatchService, ServingConfig
            from ncnet_tpu.utils.faults import paced_burst

            scfg = ServingConfig(
                max_queue=128, max_batch=8,
                # the closed-loop capacity phase deliberately saturates
                # from ONE client; the per-client fairness cap must sit
                # above the queue bound or it would shed the probe itself
                max_in_flight_per_client=256,
                buckets=((IMAGE, IMAGE),), max_buckets=2,
                warm_buckets=((IMAGE, IMAGE),),
                # the live telemetry plane rides along: the scrape-cost
                # metric below prices it, and the SLO tracker feeds the
                # budget-burn gate (objective = the latency digest range —
                # generous, so burn only moves when serving actually
                # breaks: deadline blows, admitted sheds, quarantines)
                introspect_port=0, slo_ms=2000.0,
            )
            service = MatchService(cfg16, params, scfg).start()
            try:
                rng_l = np.random.default_rng(11)
                pairs = [
                    (rng_l.integers(0, 255, (IMAGE, IMAGE, 3), dtype=np.uint8),
                     rng_l.integers(0, 255, (IMAGE, IMAGE, 3), dtype=np.uint8))
                    for _ in range(8)
                ]
                # closed-loop capacity: saturate the pipeline, measure drain
                t0 = time.perf_counter()
                futs = [service.submit(*pairs[i % 8]) for i in range(32)]
                for f in futs:
                    f.result(timeout=300)
                cap_qps = 32 / (time.perf_counter() - t0)
                # open loop at 70% of capacity: offered rate is pinned, so
                # the latencies include real queueing delay
                counter = itertools.count()
                submit = lambda: service.submit(  # noqa: E731
                    *pairs[next(counter) % 8])
                rate = max(cap_qps * 0.7, 1.0)
                n_offered = max(int(rate * 4), 16)
                t0 = time.perf_counter()
                futs, _ = paced_burst(submit, rate, n_offered)
                lat = []
                for f in futs:
                    try:
                        lat.append(f.result(timeout=300).wall_s * 1e3)
                    except Exception:  # noqa: BLE001 — count successes only
                        pass
                span = time.perf_counter() - t0
                if not lat:
                    raise RuntimeError("no serving results at 70% load")
                out = {
                    "serve_capacity_qps": round(cap_qps, 2),
                    "serve_qps": round(len(lat) / span, 2),
                    "serve_p50_ms": round(float(np.percentile(lat, 50)), 2),
                    "serve_p95_ms": round(float(np.percentile(lat, 95)), 2),
                    "serve_p99_ms": round(float(np.percentile(lat, 99)), 2),
                }
                # overload: ~2 s PACED at 3x capacity — paced_burst's
                # docstring explains why pacing makes shed_pct pin to the
                # overload factor (gate-sound lower-is-better) instead of
                # scaling with absolute capacity
                burst_rate = cap_qps * 3
                n_burst = max(int(burst_rate * 2), 64)
                futs_b, sheds_b = paced_burst(submit, burst_rate, n_burst)
                for f in futs_b:
                    try:
                        f.result(timeout=300)
                    except Exception:  # noqa: BLE001 — outcome only
                        pass
                out["serve_shed_pct"] = round(
                    100.0 * len(sheds_b) / n_burst, 2)
                # live-plane cost + SLO burn (ISSUE 11): one /metrics
                # scrape per serving scenario.  The plane must be FREE —
                # a scrape that costs a meaningful fraction of the batch
                # cadence would perturb the very latencies it reports, so
                # the bench hard-fails at 1% rather than quietly shipping
                # a heavy endpoint.
                if service.introspect_url is not None:
                    from ncnet_tpu.serving.introspect import scrape_wall_ms

                    scrape_ms = scrape_wall_ms(service.introspect_url)
                    out["serve_scrape_wall_ms"] = round(scrape_ms, 3)
                    batch_snap = service.metrics().get("batch_wall_s", {})
                    cadence_ms = 1e3 * batch_snap.get(
                        "p50_s", batch_snap.get("mean_s", 0.0))
                    if cadence_ms and scrape_ms >= 0.01 * cadence_ms:
                        raise RuntimeError(
                            f"/metrics scrape costs {scrape_ms:.3f} ms — "
                            f">=1% of the {cadence_ms:.1f} ms batch "
                            "cadence; the telemetry plane must be free")
                # trace-header wire cost (ISSUE 20): the pod trace context
                # rides every request as an ADDITIVE wire-header field, so
                # its price is the codec wall.  Differencing two full-size
                # codec walls is hopeless here — the header costs ~1 us
                # against a ~1 ms wall, far below big-buffer alloc jitter
                # (observed +-10% swings would spuriously trip the gate).
                # Instead measure the header's MARGINAL cost on a tiny
                # fixed payload (interleaved min-of-chunks, tight timing —
                # the header cost is payload-independent: one extra dict
                # field encoded + parsed), then normalize by the real
                # image-size codec wall, where noise only touches the
                # denominator.  Same contract as the scrape gate above:
                # observability must be FREE, so the bench hard-fails at
                # 1% rather than quietly taxing every request on the wire.
                from ncnet_tpu.observability.tracing import new_trace
                from ncnet_tpu.serving.wire import (decode_request,
                                                    encode_request)

                hdr = new_trace().to_header()
                src_w, tgt_w = pairs[0]
                tiny = np.zeros((8, 8, 3), dtype=np.uint8)

                def _codec_wall(img_a, img_b, trace, iters):
                    t0 = time.perf_counter()
                    for _ in range(iters):
                        decode_request(encode_request(
                            img_a, img_b, client="bench",
                            request_id="t0", trace=trace))
                    return (time.perf_counter() - t0) / iters

                bare_wall = traced_wall = float("inf")
                for _ in range(7):
                    bare_wall = min(
                        bare_wall, _codec_wall(tiny, tiny, None, 200))
                    traced_wall = min(
                        traced_wall, _codec_wall(tiny, tiny, hdr, 200))
                header_cost_s = traced_wall - bare_wall
                base_wall = min(
                    _codec_wall(src_w, tgt_w, None, 20) for _ in range(3))
                overhead = (100.0 * header_cost_s / base_wall
                            if base_wall > 0 else 0.0)
                # clamp at 0 for the store: "traced was measurably FASTER"
                # is timing noise, and a negative floor would let real
                # regressions hide behind one lucky baseline
                out["serve_trace_overhead_pct"] = round(max(overhead, 0.0),
                                                        3)
                if overhead >= 1.0:
                    raise RuntimeError(
                        f"trace header costs {overhead:.2f}% of the wire "
                        f"codec wall ({header_cost_s * 1e6:.3f} us on a "
                        f"{base_wall * 1e3:.3f} ms round trip) — >= 1%; "
                        "the trace context must be free on the wire")
                # cumulative error-budget burn over every phase above
                # (lower-is-better in the perf store via the burn_pct
                # token): 0 while serving keeps its promises, jumps the
                # run something starts deadline-blowing or shedding
                # admitted work
                out["slo_budget_burn_pct"] = \
                    service.health()["slo"]["budget_burn_pct"]
            finally:
                service.stop()
            # replica-pool scaling (ISSUE 10): closed-loop capacity at pool
            # sizes 1/2/4 (bounded by visible devices — on a single-device
            # host only r1 is honest), each pool a fresh service with one
            # engine per device.  serve_capacity_qps_r{k} land in the perf
            # store (qps → higher-is-better) so perf_regress --check gates
            # pool SCALING, not just single-engine capacity.  r1 IS the
            # single-engine closed loop just measured — aliased, not paid
            # for twice (a second warmup + 32 requests for the same number)
            out["serve_capacity_qps_r1"] = out["serve_capacity_qps"]
            ndev = len(jax.devices())
            for k in (2, 4):
                if k > ndev:
                    break
                # each pool size isolated: an r4 OOM/compile failure must
                # not discard the single-engine metrics already measured
                # above (nor the smaller pools') by re-raising into
                # _with_retries' whole-function retry
                try:
                    scfg_k = ServingConfig(
                        max_queue=128, max_batch=8,
                        max_in_flight_per_client=256,
                        buckets=((IMAGE, IMAGE),), max_buckets=2,
                        warm_buckets=((IMAGE, IMAGE),), replicas=k,
                    )
                    service_k = MatchService(cfg16, params, scfg_k).start()
                    try:
                        t0 = time.perf_counter()
                        futs = [service_k.submit(*pairs[i % 8])
                                for i in range(32)]
                        for f in futs:
                            f.result(timeout=300)
                        out[f"serve_capacity_qps_r{k}"] = round(
                            32 / (time.perf_counter() - t0), 2)
                    finally:
                        service_k.stop()
                except Exception as e:  # noqa: BLE001 — partial sweep is
                    # still a valid artifact
                    import sys as _sys

                    print(f"bench serve pool r{k} failed "
                          f"({type(e).__name__}: {str(e)[:200]}); keeping "
                          "the metrics already measured", file=_sys.stderr)
            return out

        out = _with_retries(_serving_metrics, label="serving") or {}
        res.update(out)

    # open-loop streaming scenario (ISSUE 19, serving/stream.py): two
    # concurrent camera streams with bursty/jittered arrivals driven
    # through MatchService.stream_submit at a tracking-feasible bucket,
    # with one scene cut injected per stream.  Extras: the steady-frame
    # p95 wall on the TRACKED (coarse-pass-free) path, the injected cut's
    # recovery wall (the exact coarse-to-fine fallback frame), the
    # coarse-skip fraction, and the per-frame coarse-to-fine wall at the
    # SAME shape as the reference the steady wall must beat.  All four are
    # perf-store-ingested (`_ms` lower, `skip_pct` higher), so
    # perf_regress --check gates the steady-state win.  TPU-gated like the
    # serving scenario; NCNET_BENCH_STREAM=1 forces it elsewhere (the
    # CPU-forced run is the acceptance evidence that the tracked wall sits
    # strictly below the coarse-to-fine wall).
    flag = os.environ.get("NCNET_BENCH_STREAM")
    on_tpu = "TPU" in jax.devices()[0].device_kind
    if (flag not in ("0", "") if flag is not None else on_tpu) \
            and res.get("stream_steady_p95_ms") is None:

        def _stream_metrics():
            from ncnet_tpu.serving import MatchService, ServingConfig
            from ncnet_tpu.serving.stream import run_stream_load

            # stride-16 grid must divide by the coarse factor and fit the
            # fine-tile patch: 192 -> 12x12 fine, 6x6 coarse at factor 2;
            # track_radius stays at the steady-frame default (0: one tile
            # per cell — the configuration whose wall undercuts c2f)
            side = 192
            cfg_tr = cfg16.replace(sparse_topk=_SPARSE_K)
            scfg = ServingConfig(
                max_queue=128, max_batch=4, max_in_flight_per_client=256,
                buckets=((side, side),), max_buckets=2,
                warm_buckets=((side, side),), slo_ms=5000.0)
            service = MatchService(cfg_tr, params, scfg).start()
            try:
                n_streams, n_frames, cut_at = 2, 14, 9
                rng_s = np.random.default_rng(23)
                refs = [rng_s.integers(0, 255, (side, side, 3),
                                       dtype=np.uint8)
                        for _ in range(n_streams)]
                # frames pre-generated (frame_fn runs on per-stream
                # threads; a shared Generator is not thread-safe): small
                # jitter around the reference = steady, one unrelated
                # image = the injected cut
                tgts = [[(rng_s.integers(0, 255, (side, side, 3),
                                         dtype=np.uint8)
                          if fi == cut_at else
                          np.clip(refs[si].astype(np.int16)
                                  + rng_s.integers(-3, 4, refs[si].shape),
                                  0, 255).astype(np.uint8))
                         for fi in range(n_frames)]
                        for si in range(n_streams)]
                recs = run_stream_load(
                    service, lambda si, fi: (refs[si], tgts[si][fi]),
                    streams=n_streams, frames=n_frames, rate_hz=8.0,
                    jitter=0.3, burst_every=4, seed=23)
                served = [r for r in recs if r["outcome"] == "result"]
                steady = [r["wall_ms"] for r in served
                          if r["tracked"] and not r["fallback"]]
                cuts = [r["wall_ms"] for r in served if r["fallback"]]
                if not steady or not cuts:
                    raise RuntimeError(
                        f"stream scenario degenerate: {len(steady)} "
                        f"tracked / {len(cuts)} fallback frames")
                # the reference: per-frame coarse-to-fine walls for the
                # SAME pairs through the plain (non-stream) path
                c2f = []
                for i in range(6):
                    r = service.submit(
                        refs[i % n_streams],
                        tgts[i % n_streams][i % cut_at]).result(timeout=300)
                    c2f.append(r.wall_s * 1e3)
                out = {
                    "stream_steady_p95_ms": round(
                        float(np.percentile(steady, 95)), 2),
                    "stream_cut_recovery_ms": round(
                        float(np.median(cuts)), 2),
                    "stream_coarse_skip_pct": round(
                        100.0 * len(steady) / len(served), 2),
                    "stream_c2f_frame_ms": round(
                        float(np.median(c2f)), 2),
                }
            finally:
                service.stop()
            return out

        out = _with_retries(_stream_metrics, label="streaming") or {}
        res.update(out)

    # multi-host router scenario (ISSUE 12): h backend PROCESSES behind a
    # serving/router.py::MatchRouter — closed-loop capacity at pod sizes
    # h=1,2 (route_capacity_qps_h{k}: the fan-out scaling trajectory),
    # open-loop p95 at 70% of the h=2 capacity (route_p95_ms: queueing +
    # wire + routing overhead measured, not hidden), and the shed fraction
    # under a pinned ~3x paced burst (route_shed_pct).  Backends are
    # CPU-forced tiny-arch subprocesses ON PURPOSE: two processes cannot
    # share one TPU, and the quantity this family gates is the WIRE+ROUTER
    # overhead trajectory (framing, HTTP, scoring, failover bookkeeping),
    # which is device-independent — perf_regress --check gates it with the
    # inferred directions (qps higher, _ms lower, shed_pct lower).
    flag = os.environ.get("NCNET_BENCH_SERVE")
    on_tpu = "TPU" in jax.devices()[0].device_kind
    if (flag not in ("0", "") if flag is not None else on_tpu) \
            and res.get("route_capacity_qps_h1") is None:

        def _router_metrics():
            import sys as _sys

            _tools = os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools")
            if _tools not in _sys.path:
                _sys.path.insert(0, _tools)
            import serve_probe as _sp

            from ncnet_tpu.serving import MatchRouter, RouterConfig
            from ncnet_tpu.utils.faults import paced_burst

            side = 64
            rng_r = np.random.default_rng(17)
            pairs = [
                (rng_r.integers(0, 255, (side, side, 3), dtype=np.uint8),
                 rng_r.integers(0, 255, (side, side, 3), dtype=np.uint8))
                for _ in range(8)
            ]
            out = {}
            for h in (1, 2):
                procs = _sp.spawn_backends(h, side)
                router = None
                try:
                    # router construction INSIDE the try: a ctor/start
                    # failure must still SIGTERM the spawned backends, or
                    # orphaned resident processes skew every later metric
                    router = MatchRouter(
                        [u for _, u in procs],
                        RouterConfig(max_queue=128,
                                     max_in_flight_per_client=256),
                    ).start()
                    t0 = time.perf_counter()
                    futs = [router.submit(*pairs[i % 8])
                            for i in range(32)]
                    for f in futs:
                        f.result(timeout=300)
                    cap = 32 / (time.perf_counter() - t0)
                    out[f"route_capacity_qps_h{h}"] = round(cap, 2)
                    if h == 2:
                        # open loop at 70% of pod capacity: pinned offered
                        # rate, so p95 includes real queueing + wire delay
                        import itertools

                        counter = itertools.count()
                        submit = lambda: router.submit(  # noqa: E731
                            *pairs[next(counter) % 8])
                        rate = max(cap * 0.7, 1.0)
                        futs, _ = paced_burst(
                            submit, rate, max(int(rate * 4), 16))
                        lat = []
                        for f in futs:
                            try:
                                lat.append(
                                    f.result(timeout=300).wall_s * 1e3)
                            except Exception:  # noqa: BLE001 — successes
                                pass
                        if lat:
                            out["route_p95_ms"] = round(
                                float(np.percentile(lat, 95)), 2)
                        # ~2 s paced at 3x pod capacity: the shed wall
                        burst_rate = cap * 3
                        n_burst = max(int(burst_rate * 2), 64)
                        futs_b, sheds_b = paced_burst(
                            submit, burst_rate, n_burst)
                        for f in futs_b:
                            try:
                                f.result(timeout=300)
                            except Exception:  # noqa: BLE001 — outcomes
                                pass
                        out["route_shed_pct"] = round(
                            100.0 * len(sheds_b) / n_burst, 2)
                finally:
                    if router is not None:
                        router.stop()
                    _sp.stop_backends(procs)
            return out

        try:
            res.update(_router_metrics())
        except Exception as e:  # noqa: BLE001 — a router-scenario failure
            # must not discard the serving metrics already measured
            import sys as _sys

            print(f"bench router scenario failed ({type(e).__name__}: "
                  f"{str(e)[:200]}); keeping the metrics already measured",
                  file=_sys.stderr)
    for k in [k for k, v in res.items() if v is None]:  # prune in place so a
        del res[k]  # shared res dict keeps already-captured metrics on retry

    # train step (BASELINE north-star: image-pairs/sec at the reference's
    # bs=16 recipe, train.py:39-43).  The volume-chunked gradient-
    # accumulation path (training/loss.py weak_loss_and_grads, r4) caps live
    # memory at one chunk, so the full reference batch fits one 16G chip in
    # BOTH precisions — the ladder is only a compile-failure fallback.
    # Since r7 the bf16 step routes the NC filter through the resident
    # Pallas forward + backward where the compile probes pass
    # (ops/nc_fused_lane_vjp.py): r6 measured 1148.9 ms at bs=16 (~72
    # ms/pair fp32, 17.2 pairs/s bf16) with the backward on the XLA conv4d
    # formulations — ~10× the ~6 forward-equivalents a pos+neg weak step
    # should cost; the fwd/bwd/update decomposition and train_bf16_mfu_pct
    # below attribute whatever gap remains.
    def measure_train(bs_try, half, fold_pos_neg=None):
        """Full-step ms; ``fold_pos_neg`` not None pins the WHOLE-BATCH
        backward (accum_chunks=0) with/without the pos+neg fold — the
        evidence pair for flipping the fold default next TPU session."""
        tcfg = TrainConfig(
            model=cfg.replace(half_precision=half), batch_size=bs_try,
            data_parallel=False,
            **({} if fold_pos_neg is None
               else {"accum_chunks": 0, "fold_pos_neg": fold_pos_neg}),
        )
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            state, optimizer, mcfg, _ = training.create_train_state(tcfg)
        step = training.make_train_step(
            mcfg, optimizer, donate=False, stop_backbone_grad=True,
            accum_chunks=tcfg.accum_chunks,
            fold_pos_neg=tcfg.fold_pos_neg,
        )

        def train_out(src, tgt):
            new_state, loss = step(
                state, {"source_image": src, "target_image": tgt}
            )
            # consume the UPDATED trainable params, not just the loss —
            # otherwise XLA dead-code-eliminates the whole backward pass
            # + optimizer update and this measures a forward-only step
            nc_dep = sum(
                jnp.sum(leaf.astype(jnp.float32))
                for layer in new_state.params["nc"]
                for leaf in layer.values()
            )
            return loss.astype(jnp.float32) + nc_dep * 1e-6

        ms = _timeit_scan(
            chain_step(train_out), image_pair_input(bs_try), n_long=4, reps=3
        )
        if ms <= 0:  # all reps jitter-corrupted: don't emit garbage
            raise RuntimeError(f"non-positive train timing {ms}")
        return ms

    for bs_try in ((16, 8, 4) if res.get("train_pairs_per_sec") is None
                   else ()):
        try:
            ms = measure_train(bs_try, half=False)
            res["train_pairs_per_sec"] = bs_try / (ms * 1e-3)
            res["train_step_ms"] = ms
            res["train_batch_size"] = bs_try
            break
        except Exception as e:
            # fallback path: a failed compile/OOM → retry smaller.  Anything
            # else is still printed so breakage can't hide as "didn't fit"
            # (stdout stays reserved for the one JSON line).
            import sys

            print(f"train bench bs={bs_try} failed: {str(e)[:200]}",
                  file=sys.stderr)
            continue
    if res.get("train_pairs_per_sec_bf16") is None:
        # same ladder fallback as fp32, starting from the size fp32 landed on
        start = res.get("train_batch_size", 16)
        for bs_try in [b for b in (16, 8, 4) if b <= start]:
            try:
                ms = measure_train(bs_try, half=True)
                res["train_pairs_per_sec_bf16"] = bs_try / (ms * 1e-3)
                res["train_step_ms_bf16"] = ms
                res["train_batch_size_bf16"] = bs_try
                break
            except Exception as e:
                import sys

                print(f"train bench bf16 bs={bs_try} failed: {str(e)[:200]}",
                      file=sys.stderr)

    # fwd/bwd/update device-wall decomposition of the bf16 step (ISSUE r7)
    # by prefix differencing, like the filter_stage_* metrics: forward =
    # the loss value alone, backward = (loss+grads) − forward, update =
    # full step − (loss+grads).  Plus train_bf16_mfu_pct on the
    # 6×-filter-FLOP algorithmic basis (a pos+neg weak step is 2 symmetric
    # filter forwards + a ~2×-forward backward = 6 filter-equivalents;
    # backbone/correlation/score are <5% of that).  TPU-gated like the
    # InLoc metric — two extra steady-state compiles; NCNET_BENCH_TRAIN_
    # BREAKDOWN=1 forces it elsewhere.
    flag = os.environ.get("NCNET_BENCH_TRAIN_BREAKDOWN")
    want_breakdown = (flag not in ("0", "") if flag is not None
                      else "TPU" in jax.devices()[0].device_kind)
    if want_breakdown and res.get("train_step_ms_bf16") is not None \
            and res.get("train_bwd_ms_bf16") is None:
        def _train_parts():
            from ncnet_tpu.training.loss import weak_loss, weak_loss_and_grads

            bs = res["train_batch_size_bf16"]
            tcfg = TrainConfig(
                model=cfg.replace(half_precision=True), batch_size=bs,
                data_parallel=False,
            )
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                state, _, mcfg, _ = training.create_train_state(tcfg)

            def fwd_out(src, tgt):
                return weak_loss(
                    mcfg, state.params,
                    {"source_image": src, "target_image": tgt},
                    stop_backbone_grad=True,
                )[None]

            def grads_out(src, tgt):
                loss, g = weak_loss_and_grads(
                    mcfg, state.params,
                    {"source_image": src, "target_image": tgt},
                )
                dep = sum(
                    jnp.sum(leaf.astype(jnp.float32))
                    for layer in g["nc"] for leaf in layer.values()
                )
                return (loss.astype(jnp.float32) + dep * 1e-6)[None]

            fwd_ms = _timeit_scan(
                chain_step(fwd_out), image_pair_input(bs), n_long=4, reps=3)
            grads_ms = _timeit_scan(
                chain_step(grads_out), image_pair_input(bs), n_long=4, reps=3)
            step_ms = res["train_step_ms_bf16"]
            res["train_fwd_ms_bf16"] = round(fwd_ms, 2)
            res["train_bwd_ms_bf16"] = round(max(grads_ms - fwd_ms, 0.0), 2)
            res["train_update_ms_bf16"] = round(max(step_ms - grads_ms, 0.0), 2)
            feat_shape = jax.eval_shape(
                lambda p, x: extract_features(cfg, p, x),
                params, jax.ShapeDtypeStruct((1, IMAGE, IMAGE, 3), jnp.float32),
            ).shape
            peak = _PEAK_TFLOPS.get(jax.devices()[0].device_kind)
            if peak:
                step_flops = 6 * _arch_filter_flops(feat_shape[1])
                res["train_bf16_mfu_pct"] = round(
                    100 * (step_flops / (step_ms / bs * 1e-3) / 1e12) / peak,
                    2)
            return True

        _with_retries(_train_parts, label="train_breakdown")

    # folded vs unfolded whole-batch backward (ISSUE r7 satellite): the
    # fold measured NO faster on the r4 XLA backward; the resident Pallas
    # VJP changes the trade (one 2B-volume backward program per chunk), so
    # re-measure both so the fold_pos_neg default can flip on evidence.
    # Whole-batch backward programs historically stressed the tunnel
    # compile-helper, hence the small batch and per-metric retries.
    if want_breakdown and res.get("train_step_ms_bf16") is not None:
        fold_bs = min(res.get("train_batch_size_bf16", 8), 8)
        for key_name, fold in (("train_step_ms_bf16_foldpn", True),
                               ("train_step_ms_bf16_unfolded", False)):
            put(key_name,
                lambda fold=fold: measure_train(fold_bs, half=True,
                                                fold_pos_neg=fold),
                label=key_name)

    # the run's measured HBM high-water mark, taken LAST so it covers
    # every program the bench executed (None on backends without
    # memory_stats — the metric is simply absent)
    try:
        from ncnet_tpu.observability.memory import hbm_stats

        stats = hbm_stats()
        if stats and stats.get("peak_bytes_in_use") is not None:
            res["mem_peak_hbm_bytes"] = stats["peak_bytes_in_use"]
    except Exception:
        pass
    return res


def _bench_inloc_matcher():
    """Warm seconds/pair for the full InLoc-resolution eval unit: raw uint8
    in, device normalize+quantized-resize, bf16 k=2 forward over the pooled
    56M-cell volume, both-direction match extraction, host sort/dedup
    (ncnet_tpu/evaluation/inloc.py make_pair_matcher)."""
    import time as _time
    import warnings

    import jax
    import numpy as np

    from ncnet_tpu.config import ModelConfig
    from ncnet_tpu import models
    from ncnet_tpu.evaluation.inloc import make_pair_matcher

    cfg = ModelConfig(
        ncons_kernel_sizes=(3, 3), ncons_channels=(16, 1),  # IVD arch
        half_precision=True, backbone_bf16=True, relocalization_k_size=2,
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        params = models.init_ncnet(cfg, jax.random.key(0))
    matcher = make_pair_matcher(
        cfg, params, do_softmax=True, both_directions=True,
        flip_direction=False, preprocess_image_size=3200,
    )
    rng = np.random.default_rng(0)
    q = rng.integers(0, 255, (1, 4032, 3024, 3), dtype=np.uint8)
    dbs = [
        rng.integers(0, 255, (1, 1200, 1600, 3), dtype=np.uint8)
        for _ in range(10)
    ]
    src = matcher.preprocess(q)
    matcher(src, dbs[0])  # compile + first-touch uploads
    matcher(src, dbs[0])  # settle the shape-bucket caches
    # steady-state pairs/s of the depth-2 pipeline the eval loop runs
    # (run_inloc_eval): dispatch pair i+1 before fetching pair i, so upload
    # and dispatch latency hide behind device compute.  Per-fetch timestamps
    # give the p50/p95 latency spread alongside the mean.
    t0 = _time.perf_counter()
    ticks = []
    in_flight = []
    for db in dbs:
        in_flight.append(matcher.dispatch(src, db))
        if len(in_flight) > 1:
            matcher.fetch(in_flight.pop(0))
            ticks.append(_time.perf_counter())
    while in_flight:
        matcher.fetch(in_flight.pop(0))
        ticks.append(_time.perf_counter())
    per_pair = np.diff(np.asarray([t0] + ticks))
    mean_s = (ticks[-1] - t0) / len(dbs)
    return (
        mean_s,
        float(np.percentile(per_pair, 50)),
        float(np.percentile(per_pair, 95)),
    )


def _bench_store_cached_query():
    """``(cold_query_s, warm_query_s, hit_pct)`` for one full InLoc query
    (10 panos, depth-2 pipeline — the run_inloc_eval unit) against the
    persistent feature store (ncnet_tpu/store/): cold = every pano feature
    computed and atomically committed; warm = every pano a verified store
    hit, so the query performs exactly ONE backbone extraction (its own).
    Compiles are charged to a warm-up pano outside the measured set; the
    hit fraction is deterministic by construction (warm-up: 1 miss + 1
    hit; cold pass: 10 misses; warm pass: 10 hits → 50.0%), so the
    perf-store series gates cache effectiveness, not traffic luck."""
    import shutil
    import tempfile
    import time as _time
    import warnings

    import jax
    import numpy as np

    from ncnet_tpu.config import ModelConfig
    from ncnet_tpu import models
    from ncnet_tpu.evaluation.inloc import make_pair_matcher
    from ncnet_tpu.store import FeatureStore, backbone_fingerprint

    cfg = ModelConfig(
        ncons_kernel_sizes=(3, 3), ncons_channels=(16, 1),  # IVD arch
        half_precision=True, backbone_bf16=True, relocalization_k_size=2,
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        params = models.init_ncnet(cfg, jax.random.key(0))
    root = tempfile.mkdtemp(prefix="bench_fstore_")
    try:
        store = FeatureStore(
            root,
            backbone_fingerprint(params, image_size=3200, k_size=2,
                                 dtype="bf16"),
            scope="bench")
        matcher = make_pair_matcher(
            cfg, params, do_softmax=True, both_directions=True,
            flip_direction=False, preprocess_image_size=3200, store=store,
        )
        rng = np.random.default_rng(0)
        q = rng.integers(0, 255, (1, 4032, 3024, 3), dtype=np.uint8)
        dbs = [
            rng.integers(0, 255, (1, 1200, 1600, 3), dtype=np.uint8)
            for _ in range(10)
        ]
        warm_pano = rng.integers(0, 255, (1, 1200, 1600, 3), dtype=np.uint8)
        src = matcher.preprocess(q)
        # compile + first-touch uploads charged here, NOT to either pass
        matcher(src, matcher.prepare_db(warm_pano))
        matcher(src, matcher.prepare_db(warm_pano))

        def one_query():
            t0 = _time.perf_counter()
            in_flight = []
            for db in dbs:
                in_flight.append(
                    matcher.dispatch(src, matcher.prepare_db(db)))
                if len(in_flight) > 1:
                    matcher.fetch(in_flight.pop(0))
            while in_flight:
                matcher.fetch(in_flight.pop(0))
            return _time.perf_counter() - t0

        cold_s = one_query()   # 10 misses: extract + commit per pano
        warm_s = one_query()   # 10 verified hits: zero db-side extractions
        hit_pct = store.hit_pct()
        store.close()
        return cold_s, warm_s, hit_pct
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_torch_reference_style(iters=3):
    """ms per pair, batch 1, for a reference-style torch CPU forward (random
    weights; timing only), with warm-up and averaging.  Mirrors the
    reference's structure, not its code: frozen NCHW ResNet-101[:layer3], bmm
    4D correlation, mutual matching, and the conv4d-as-Python-loop-over-conv3d
    neighbourhood consensus."""
    import torch
    import torch.nn.functional as F

    torch.manual_seed(0)

    def conv_w(cout, cin, k):
        return torch.randn(cout, cin, k, k) * 0.05

    stages = {"layer1": (3, 64), "layer2": (4, 128), "layer3": (23, 256)}
    sd = {"conv1": conv_w(64, 3, 7)}
    inplanes = 64
    for s, (n, planes) in stages.items():
        for i in range(n):
            sd[f"{s}.{i}.c1"] = conv_w(planes, inplanes, 1)
            sd[f"{s}.{i}.c2"] = conv_w(planes, planes, 3)
            sd[f"{s}.{i}.c3"] = conv_w(planes * 4, planes, 1)
            if i == 0:
                sd[f"{s}.{i}.ds"] = conv_w(planes * 4, inplanes, 1)
                inplanes = planes * 4

    def backbone(x):
        x = F.relu(F.conv2d(x, sd["conv1"], stride=2, padding=3))
        x = F.max_pool2d(x, 3, 2, 1)
        for s, (n, _) in stages.items():
            for i in range(n):
                stride = 2 if (i == 0 and s != "layer1") else 1
                out = F.relu(F.conv2d(x, sd[f"{s}.{i}.c1"]))
                out = F.relu(F.conv2d(out, sd[f"{s}.{i}.c2"], stride=stride, padding=1))
                out = F.conv2d(out, sd[f"{s}.{i}.c3"])
                if f"{s}.{i}.ds" in sd:
                    x = F.conv2d(x, sd[f"{s}.{i}.ds"], stride=stride)
                x = F.relu(out + x)
        return F.normalize(x, dim=1)

    nc_w, nc_b = [], []
    cin = 1
    for k, cout in zip(KERNELS, CHANNELS):
        nc_w.append(torch.randn(cout, cin, k, k, k, k) * 0.05)
        nc_b.append(torch.zeros(cout))
        cin = cout

    def conv4d_loop(x, w, b):
        # the reference's structure: slice dim 2, conv3d per tap, accumulate
        bsz, cin_, ha, wa, hb, wb = x.shape
        cout, _, ka, kwa, kb, kwb = w.shape
        pad = ka // 2
        xp = F.pad(x, (0, 0, 0, 0, 0, 0, pad, pad))  # pad hA
        out = torch.zeros(bsz, cout, ha, wa, hb, wb)
        for i in range(ha):
            acc = None
            for p in range(ka):
                o = F.conv3d(xp[:, :, i + p], w[:, :, p], bias=None, padding=kwa // 2)
                acc = o if acc is None else acc + o
            out[:, :, i] = acc + b.view(1, -1, 1, 1, 1)
        return out

    def mutual(c):
        bsz, _, ha, wa, hb, wb = c.shape
        mb = c.view(bsz, ha * wa, hb, wb).max(1, keepdim=True)[0].view(bsz, 1, 1, 1, hb, wb)
        ma = c.view(bsz, ha, wa, hb * wb).max(3, keepdim=True)[0].view(bsz, 1, ha, wa, 1, 1)
        return c * (c / (mb + 1e-5)) * (c / (ma + 1e-5))

    def forward():
        x = torch.rand(1, 3, IMAGE, IMAGE)
        y = torch.rand(1, 3, IMAGE, IMAGE)
        with torch.no_grad():
            fa, fb = backbone(x), backbone(y)
            bsz, c, h, w = fa.shape
            corr = torch.bmm(
                fa.view(bsz, c, h * w).transpose(1, 2), fb.view(bsz, c, h * w)
            ).view(bsz, 1, h, w, h, w)
            corr = mutual(corr)
            v = corr
            for wgt, bias in zip(nc_w, nc_b):
                v = F.relu(conv4d_loop(v, wgt, bias))
            v2 = corr.permute(0, 1, 4, 5, 2, 3)
            for wgt, bias in zip(nc_w, nc_b):
                v2 = F.relu(conv4d_loop(v2, wgt, bias))
            return mutual(v + v2.permute(0, 1, 4, 5, 2, 3))

    forward()  # warm-up
    t0 = time.perf_counter()
    for _ in range(iters):
        forward()
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    """Always print exactly one JSON line and exit 0.

    Per-metric retries live in bench_jax(); this level adds one retry of the
    whole JAX side (a tunnel failure during init nullified round 2's artifact)
    and guarantees the artifact carries whatever metrics survived — value is
    null only if literally everything failed.
    """
    import sys

    res = {}
    for attempt in range(2):
        try:
            bench_jax(res)
            break
        except Exception as e:
            print(f"bench_jax attempt {attempt + 1}/2 failed: {str(e)[:300]}",
                  file=sys.stderr)
            if attempt == 0:
                time.sleep(15)
    try:
        baseline_ms = bench_torch_reference_style()
        res["torch_cpu_ms_per_pair_bs1"] = round(baseline_ms, 1)
        vs_baseline = round(baseline_ms / res["forward_ms_per_pair_bs1"], 2)
    except Exception:
        vs_baseline = None
    headline = res.pop("forward_ms_per_pair_fp32", None)

    def jsonable(v):
        """Round floats, coerce numpy scalars; None when unserializable so
        one stray value drops only itself, never the whole artifact."""
        try:
            v = round(float(v), 3) if not isinstance(v, (str, int)) else v
            json.dumps(v)
            return v
        except Exception:
            return None

    extra = {k: j for k, v in res.items() if (j := jsonable(v)) is not None}
    # schema envelope (round 8): the artifact carries the same run envelope
    # as the observability event log — schema version, run id, host, device
    # kind — plus the git rev, so BENCH_r*.json and run telemetry share one
    # attributable format.  The metric/value/unit/vs_baseline/extra keys are
    # unchanged (the harness's parse stays bit-compatible); the metrics also
    # flow through a MetricsRegistry, so a bound event sink (a harness that
    # wants bench runs in its event log) records them as a `metrics` event.
    from ncnet_tpu.observability.events import git_revision, run_envelope
    from ncnet_tpu.observability.metrics import MetricsRegistry

    envelope = run_envelope()
    rev = git_revision()
    if rev:
        envelope["git_rev"] = rev
    registry = MetricsRegistry(scope="bench")
    for k, v in extra.items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            registry.gauge(k).set(v)
    if headline is not None and jsonable(headline) is not None:
        registry.gauge("pf_pascal_forward_ms_per_pair").set(jsonable(headline))
    registry.flush(run_id=envelope["run_id"])
    # cross-run perf history (round 9): every bench run lands in the
    # persistent store so tools/perf_regress.py can gate the next one
    # against the trailing baseline.  Fail-open; NCNET_TPU_PERF_STORE=off
    # disables.
    from ncnet_tpu.observability import perfstore

    history = {k: v for k, v in extra.items()
               if isinstance(v, (int, float)) and not isinstance(v, bool)}
    if headline is not None and jsonable(headline) is not None:
        history["pf_pascal_forward_ms_per_pair"] = jsonable(headline)
    if vs_baseline is not None and jsonable(vs_baseline) is not None:
        history["vs_baseline"] = jsonable(vs_baseline)
    perfstore.maybe_record(
        history, source="bench", run_id=envelope["run_id"],
        device_kind=envelope.get("device_kind"),
        git_rev=envelope.get("git_rev"),
    )
    print(
        json.dumps(
            {
                "metric": "pf_pascal_forward_ms_per_pair",
                "value": jsonable(headline) if headline is not None else None,
                "unit": "ms/pair",
                "vs_baseline": jsonable(vs_baseline)
                if vs_baseline is not None else None,
                "extra": extra,
                "envelope": envelope,
            }
        )
    )
    sys.exit(0)


if __name__ == "__main__":
    main()
