#!/bin/bash
# Released NCNet checkpoints (PyTorch .pth.tar — the torch importer in
# ncnet_tpu/models/checkpoint.py loads these directly), plus the torchvision
# ResNet-101 ImageNet weights used to initialize the trunk for training.
# Run from this directory: bash download.sh
set -e

wget -c https://www.di.ens.fr/willow/research/ncnet/models/ncnet_pfpascal.pth.tar
wget -c https://www.di.ens.fr/willow/research/ncnet/models/ncnet_ivd.pth.tar

# trunk weights for --backbone_weights (torchvision's public mirror)
wget -c https://download.pytorch.org/models/resnet101-63fe2227.pth
