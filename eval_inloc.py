#!/usr/bin/env python
"""Entry shim: InLoc dense-matching evaluation (see ncnet_tpu/cli/eval_inloc.py)."""
import sys

from ncnet_tpu.cli.eval_inloc import main

if __name__ == "__main__":
    sys.exit(main())
