#!/usr/bin/env python
"""InLoc localization from NCNet matches — the reference's MATLAB stage
(compute_densePE_NCNet.m) as a self-contained Python pipeline."""

from ncnet_tpu.cli.compute_localization import main

if __name__ == "__main__":
    raise SystemExit(main())
