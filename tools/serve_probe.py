#!/usr/bin/env python
"""Real-device probe of the resident match service (ncnet_tpu/serving/).

For the next TPU-attached session — the serving twin of
``nc_resident_probe`` / ``eval_faults_probe``.  Three measurements the CPU
tier-1 suite cannot make honestly:

  1. **Continuous-batching walls per shape bucket** — closed-loop streams
     per configured bucket side: batch wall, per-request latency
     percentiles, achieved qps, mean coalesced batch size.  The r05 bench
     question made concrete: how much of the ~681 ms serial bs1 wall does
     the queue+pipeline actually recover on a real tunnel?
  2. **Demotion under load** — arms ``faults.device_fail_calls`` mid-stream
     and measures the serving PAUSE (last success before the injected
     failure → first success after the demote-retrace-recompile), plus the
     outcome accounting proving zero lost requests across the recovery.
  3. **Offered-load shed behavior** — an open-loop burst at a multiple of
     measured capacity: shed fraction, admitted-work latency (the admitted
     stream must NOT deadline-blow while the overflow sheds).

  4. **Replica-pool scaling** (``--replicas 1,2,4``) — closed-loop
     capacity per pool size, one fresh service per size with one
     ``BatchMatchEngine`` per visible device: does a 4-chip pool serve ~4x
     the qps of one chip, and where does routing overhead eat the scaling?
     The numbers feed the bench's ``serve_capacity_qps_r{k}`` perf-store
     family.

  5. **Multi-host router walls** (``--router N``) — spawns N REAL backend
     subprocesses (CPU-forced host devices, tiny arch — the fan-out
     overhead is wire+routing, which is exactly what this phase prices)
     behind a ``serving/router.py::MatchRouter`` and sweeps the pod's
     walls: closed-loop capacity through the router, the failover pause
     around a SIGKILLed backend mid-stream (with the zero-lost outcome
     accounting), and the shed wall under a paced over-capacity burst.
     ``--router`` replaces the local-service phases — it measures the pod
     tier, not this process's devices.

  6. **Live-rollout walls** (``--rollout``) — a canaried old->new weight
     swap promoted through a 2-replica pool under a sustained stream: the
     rollout wall (drain + swap + bucket-ladder warmup per replica, off
     the dispatch path), the admitted stream's latency through the
     mixed-version window, per-version request accounting, and the
     zero-lost verdict.  ``--rollout --tiny`` is the tier-1 smoke of the
     PR 18 rollout plane.

Usage::

    python tools/serve_probe.py [--sides 400,512] [--pairs 48] [--tiny]
        [--no-demote] [--burst-factor 3.0] [--replicas 1,2,4]
        [--router N] [--json out.json]

``--tiny`` runs the CPU-sized smoke configuration (tiny backbone, 64 px) so
the probe's own plumbing is testable without a TPU (``--router N --tiny``
is the tier-1 smoke of the whole pod tier).  Output: one JSON document
(stdout, plus ``--json`` path).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _percentiles(xs: List[float]) -> Dict[str, float]:
    import numpy as np

    if not xs:
        return {}
    return {
        "p50": round(float(np.percentile(xs, 50)), 3),
        "p95": round(float(np.percentile(xs, 95)), 3),
        "p99": round(float(np.percentile(xs, 99)), 3),
        "mean": round(float(np.mean(xs)), 3),
        "n": len(xs),
    }


def probe(sides: List[int], n_pairs: int, tiny: bool, demote: bool,
          burst_factor: float, replicas: List[int] = (1,)) -> Dict[str, Any]:
    import warnings

    import jax
    import numpy as np

    from ncnet_tpu import models
    from ncnet_tpu.config import ModelConfig
    from ncnet_tpu.serving import MatchService, ServingConfig
    from ncnet_tpu.utils import faults
    from ncnet_tpu.utils.faults import FaultPlan, paced_burst

    if tiny:
        cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                          ncons_channels=(1,), half_precision=False)
        sides = [min(s, 64) for s in sides]
    else:
        cfg = ModelConfig(ncons_kernel_sizes=(5, 5, 5),
                          ncons_channels=(16, 16, 1),
                          half_precision=True, backbone_bf16=True)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # random-trunk warning: timing only
        params = models.init_ncnet(cfg, jax.random.key(0))

    buckets = tuple((s, s) for s in sorted(set(sides)))
    scfg = ServingConfig(
        max_queue=max(2 * n_pairs, 64), max_batch=8,
        # the closed-loop phases saturate from one client; the fairness
        # cap must exceed the stream depth or the probe sheds itself
        max_in_flight_per_client=max(2 * n_pairs, 64),
        buckets=buckets, max_buckets=len(buckets) ** 2,
        warm_buckets=buckets,
        # the live plane rides along so the probe prices a real-device
        # /metrics scrape under load (ISSUE 11's "the plane must be free")
        introspect_port=0,
    )
    out: Dict[str, Any] = {
        "device_kind": str(jax.devices()[0].device_kind),
        "tiny": tiny, "sides": sides, "n_pairs": n_pairs,
    }
    rng = np.random.default_rng(0)

    def pair(side):
        return (rng.integers(0, 255, (side, side, 3), dtype=np.uint8),
                rng.integers(0, 255, (side, side, 3), dtype=np.uint8))

    service = MatchService(cfg, params, scfg).start()
    try:
        # 1. per-bucket continuous-batching walls (closed loop)
        per_bucket: Dict[str, Any] = {}
        side_caps: Dict[int, float] = {}
        for side in sides:
            pairs = [pair(side) for _ in range(8)]
            t0 = time.perf_counter()
            futs = [service.submit(*pairs[i % 8]) for i in range(n_pairs)]
            walls = [f.result(timeout=600).wall_s * 1e3 for f in futs]
            span = time.perf_counter() - t0
            snap = service.metrics()
            batch = snap.get("batch_wall_s", {})
            side_caps[side] = n_pairs / span
            per_bucket[f"{side}x{side}"] = {
                "qps": round(side_caps[side], 2),
                "latency_ms": _percentiles(walls),
                "batch_wall_p50_ms": round(
                    1e3 * batch.get("p50_s", 0.0), 3) if batch else None,
            }
        out["buckets"] = per_bucket
        # the demotion and burst phases both drive sides[0]-shaped pairs,
        # so THAT bucket's capacity is the one their rates must key off
        cap_qps = side_caps[sides[0]]
        out["capacity_qps"] = round(cap_qps, 2)

        # live-plane scrape cost on the attached device (/metrics over
        # loopback): the same methodology the bench's 1%-of-cadence gate
        # enforces (serving/introspect.py::scrape_wall_ms), measured here
        # under the probe's own load
        if service.introspect_url is not None:
            from ncnet_tpu.serving.introspect import scrape_wall_ms

            out["scrape_wall_ms"] = round(
                scrape_wall_ms(service.introspect_url), 3)

        # 2. demotion under load: inject a device failure mid-stream and
        # time the serving pause around the demote-retrace-recompile
        if demote:
            side = sides[0]
            pairs = [pair(side) for _ in range(8)]
            # the ordinal counts process-global ResilientJit dispatches
            # from install(); ordinal 2 = the SECOND dispatched batch —
            # the first batch takes whatever is queued at dispatch time
            # (usually one request) and the rest coalesce behind it, so
            # ordinal 2 reliably exists even when batching folds the
            # whole stream into two dispatches
            faults.install(FaultPlan(device_fail_calls=(2,)))
            try:
                # more requests than one max_batch can swallow, so at
                # least two batches dispatch and the armed ordinal exists
                n_stream = max(n_pairs, 3 * scfg.max_batch)
                t0 = time.perf_counter()
                futs = [service.submit(*pairs[i % 8])
                        for i in range(n_stream)]
                ticks, outcomes = [], {"result": 0, "other": 0}
                for f in futs:
                    try:
                        f.result(timeout=600)
                        outcomes["result"] += 1
                    except Exception:  # noqa: BLE001 — classified below
                        outcomes["other"] += 1
                    ticks.append(time.perf_counter())
                gaps = np.diff(np.asarray([t0] + ticks))
                from ncnet_tpu import ops as _ops

                out["demotion"] = {
                    "outcomes": outcomes,
                    "lost": sum(1 for f in futs if f.outcome is None),
                    "pause_ms": round(float(np.max(gaps)) * 1e3, 1),
                    "median_gap_ms": round(
                        float(np.median(gaps)) * 1e3, 1),
                    "health": service.health()["state"],
                    "demoted_tiers": list(_ops.demoted_fused_tiers()),
                }
            finally:
                faults.clear()

        # 3. overload PACED at burst_factor x capacity for ~2 s — see
        # faults.paced_burst's docstring for why pacing (vs back-to-back)
        # makes shed_pct read as the overload fraction rather than scale
        # with absolute capacity
        side = sides[0]
        p0 = pair(side)
        burst_rate = max(cap_qps * burst_factor, 1.0)
        n_burst = max(int(burst_rate * 2), 32)
        futs_b, sheds = paced_burst(
            lambda: service.submit(*p0), burst_rate, n_burst)
        lat = []
        for f in futs_b:
            try:
                lat.append(f.result(timeout=600).wall_s * 1e3)
            except Exception:  # noqa: BLE001 — shed accounting below
                pass
        out["burst"] = {
            "offered": n_burst,
            "rate_qps": round(burst_rate, 2),
            "shed_pct": round(100.0 * len(sheds) / n_burst, 2),
            "admitted_latency_ms": _percentiles(lat),
            "retry_after_s": (round(sheds[0].retry_after_s, 3)
                              if sheds and sheds[0].retry_after_s else None),
        }
        out["health"] = service.health()
    finally:
        service.stop()

    # 4. replica-pool scaling sweep (ISSUE 10): closed-loop capacity per
    # pool size — the serving twin of the bench's serve_capacity_qps_r{k}
    # family.  Each pool gets a FRESH service (its own engines, committed
    # per device); capacity numbers only mean something at one replica per
    # device (replicas > devices shares devices round-robin and measures
    # pool mechanics, not hardware scaling — flagged in the output).
    if len(replicas) > 1 or replicas[0] != 1:
        import jax as _jax

        ndev = len(_jax.devices())
        sweep: Dict[str, Any] = {}
        side = sides[0]
        pairs = [pair(side) for _ in range(8)]
        for r in replicas:
            scfg_r = ServingConfig(
                max_queue=max(2 * n_pairs, 64), max_batch=8,
                max_in_flight_per_client=max(2 * n_pairs, 64),
                buckets=((side, side),), max_buckets=2,
                warm_buckets=((side, side),), replicas=r,
            )
            svc_r = MatchService(cfg, params, scfg_r).start()
            try:
                t0 = time.perf_counter()
                futs = [svc_r.submit(*pairs[i % 8]) for i in range(n_pairs)]
                walls = [f.result(timeout=600).wall_s * 1e3 for f in futs]
                span = time.perf_counter() - t0
                sweep[f"r{r}"] = {
                    "replicas": r,
                    "qps": round(n_pairs / span, 2),
                    "latency_ms": _percentiles(walls),
                    "oversubscribed": r > ndev,
                }
            finally:
                svc_r.stop()
        out["replica_sweep"] = sweep
        out["visible_devices"] = ndev
    return out


def spawn_backends(n: int, side: int, *, fake: bool = False,
                   latency_s: float = 0.02, max_queue: int = 64,
                   events_dir: Optional[str] = None):
    """Spawn ``n`` serve_backend subprocesses (CPU-forced — the pod tier's
    fan-out overhead is wire+routing, measured honestly off-device) and
    block for their startup JSON lines.  Returns ``[(Popen, url), ...]``;
    the caller owns teardown (:func:`stop_backends`).  ``events_dir``
    gives each backend its own ``--events`` log there
    (``backend<i>.jsonl``) — the per-process logs ``trace_export
    --federate`` merges into one pod trace."""
    import subprocess

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "serve_backend.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               NCNET_TPU_PERF_STORE="off", NCNET_TPU_TIER_CACHE="off")
    procs = []
    for i in range(n):
        cmd = [sys.executable, script, "--bucket-side", str(side),
               "--max-queue", str(max_queue)]
        cmd += ["--fake-engine", "--latency", str(latency_s)] if fake \
            else ["--tiny"]
        if events_dir:
            cmd += ["--events",
                    os.path.join(events_dir, f"backend{i}.jsonl")]
        procs.append(subprocess.Popen(
            cmd, stdout=subprocess.PIPE, text=True, env=env))
    out = []
    try:
        for p in procs:
            line = p.stdout.readline()
            doc = json.loads(line) if line.strip() else {}
            if "url" not in doc:
                raise RuntimeError(f"backend failed to start: {doc}")
            out.append((p, doc["url"]))
    except Exception:
        # ANY startup failure (bad bind, unparseable line) must kill the
        # WHOLE spawn set — including children not read yet — or orphaned
        # resident backends keep burning CPU under every later metric
        for p in procs:
            p.kill()
        raise
    return out


def stop_backends(procs) -> None:
    import signal as _signal

    for p, _ in procs:
        if p.poll() is None:
            p.send_signal(_signal.SIGTERM)
    for p, _ in procs:
        try:
            p.wait(timeout=20)
        except Exception:  # noqa: BLE001 — a wedged child gets the axe
            p.kill()


def probe_router(n_backends: int, side: int, n_pairs: int,
                 burst_factor: float, tiny: bool,
                 keep_logs: bool = False) -> Dict[str, Any]:
    """The pod-tier sweep: capacity/failover/shed walls through a real
    ``MatchRouter`` over ``n_backends`` spawned backend processes.
    ``keep_logs`` gives every backend its own event log in a directory
    that OUTLIVES the probe, and names the paths in the report — feed
    them straight to ``tools/trace_export.py --federate`` (plus the
    router-side log, when the caller installed a sink) for the one-pod
    Perfetto view of the sweep."""
    import numpy as np

    from ncnet_tpu.serving import MatchRouter, RouterConfig
    from ncnet_tpu.utils.faults import paced_burst

    side = min(side, 64) if tiny else side
    events_dir = None
    if keep_logs:
        import tempfile

        events_dir = tempfile.mkdtemp(prefix="serve_probe_pod_logs_")
    procs = spawn_backends(n_backends, side, events_dir=events_dir)
    rng = np.random.default_rng(0)

    def pair():
        return (rng.integers(0, 255, (side, side, 3), dtype=np.uint8),
                rng.integers(0, 255, (side, side, 3), dtype=np.uint8))

    out: Dict[str, Any] = {"backends": n_backends, "side": side,
                           "n_pairs": n_pairs}
    if events_dir:
        out["event_logs"] = [
            os.path.join(events_dir, f"backend{i}.jsonl")
            for i in range(n_backends)]
    router = None
    try:
        # router construction INSIDE the try: a ctor/start failure must
        # still SIGTERM the spawned backend processes
        router = MatchRouter(
            [url for _, url in procs],
            RouterConfig(probe_period_s=0.5, resurrect_after_s=0.5,
                         max_queue=max(2 * n_pairs, 64),
                         max_in_flight_per_client=max(2 * n_pairs, 64)),
        ).start()
        pairs = [pair() for _ in range(8)]
        # 1. closed-loop capacity through the router
        t0 = time.perf_counter()
        futs = [router.submit(*pairs[i % 8]) for i in range(n_pairs)]
        walls = [f.result(timeout=600).wall_s * 1e3 for f in futs]
        span = time.perf_counter() - t0
        cap_qps = n_pairs / span
        out["capacity_qps"] = round(cap_qps, 2)
        out["latency_ms"] = _percentiles(walls)

        # 2. failover wall: SIGKILL one backend mid-stream, measure the
        # serving pause and prove zero lost admitted requests
        if n_backends > 1:
            victim_proc, victim_url = procs[0]
            futs = [router.submit(*pairs[i % 8])
                    for i in range(max(n_pairs, 16))]
            victim_proc.kill()  # SIGKILL: no drain, no goodbye
            ticks, lost = [], 0
            t0 = time.perf_counter()
            for f in futs:
                try:
                    f.result(timeout=600)
                except Exception:  # noqa: BLE001 — classified outcomes
                    pass
                if f.outcome is None:
                    lost += 1
                ticks.append(time.perf_counter())
            gaps = np.diff(np.asarray([t0] + ticks))
            out["failover"] = {
                "killed": victim_url,
                "lost": lost,
                "pause_ms": round(float(np.max(gaps)) * 1e3, 1),
                "median_gap_ms": round(float(np.median(gaps)) * 1e3, 1),
                "router_state": router.state,
                "backend_states": {b.id: b.state
                                   for b in router.backends},
            }

        # 3. shed wall: paced burst at burst_factor x the measured
        # capacity (the paced_burst docstring explains the gate-soundness)
        p0 = pair()
        burst_rate = max(cap_qps * burst_factor, 1.0)
        n_burst = max(int(burst_rate * 2), 32)
        futs_b, sheds = paced_burst(
            lambda: router.submit(*p0), burst_rate, n_burst)
        lat = []
        for f in futs_b:
            try:
                lat.append(f.result(timeout=600).wall_s * 1e3)
            except Exception:  # noqa: BLE001 — shed accounting below
                pass
        out["burst"] = {
            "offered": n_burst,
            "rate_qps": round(burst_rate, 2),
            "shed_pct": round(100.0 * len(sheds) / n_burst, 2),
            "admitted_latency_ms": _percentiles(lat),
            "retry_after_s": (round(sheds[0].retry_after_s, 3)
                              if sheds and sheds[0].retry_after_s
                              else None),
        }
        out["health"] = router.health()
    finally:
        if router is not None:
            router.stop()
        stop_backends(procs)
    return out


def probe_rollout(side: int, n_pairs: int, tiny: bool) -> Dict[str, Any]:
    """The live-rollout sweep (PR 18): a canaried old->new weight swap
    driven while a sustained stream runs against the pool — measuring the
    thing the CPU tier cannot fake on a real device: the per-replica swap
    +warmup wall off the dispatch path, the admitted stream's latency
    through the mixed-version window, and the zero-lost outcome accounting
    across the whole promotion.  ``--tiny`` runs the same sweep as the
    tier-1 smoke of the rollout plumbing."""
    import tempfile
    import warnings

    import jax
    import numpy as np

    from ncnet_tpu import models
    from ncnet_tpu.config import ModelConfig
    from ncnet_tpu.models import checkpoint as ckpt_io
    from ncnet_tpu.serving import (
        MatchService,
        RolloutConfig,
        ServingConfig,
        resolve_serving_checkpoint,
    )

    side = min(side, 64) if tiny else side
    if tiny:
        cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                          ncons_channels=(1,), half_precision=False)
    else:
        cfg = ModelConfig(ncons_kernel_sizes=(5, 5, 5),
                          ncons_channels=(16, 16, 1),
                          half_precision=True, backbone_bf16=True)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # random-trunk warning: timing only
        params_old = models.init_ncnet(cfg, jax.random.key(0))
    # the candidate is a near-identical "fine-tune" (epsilon-perturbed, so
    # the weights digest differs and the store-detach path runs) rather
    # than a fresh init: a genuinely different random model SHOULD fail
    # the PSI gate and roll back — this probe measures the promotion walls
    params_new = jax.tree.map(lambda x: x + 1e-6, params_old)

    rng = np.random.default_rng(0)

    def pair():
        return (rng.integers(0, 255, (side, side, 3), dtype=np.uint8),
                rng.integers(0, 255, (side, side, 3), dtype=np.uint8))

    out: Dict[str, Any] = {"side": side, "tiny": tiny}
    with tempfile.TemporaryDirectory() as root:
        cand = os.path.join(root, "step_000100")
        ckpt_io.save_params(cand, cfg, params_new)
        state_path = os.path.join(root, "rollout_state.json")
        scfg = ServingConfig(
            max_queue=256, max_batch=4, max_in_flight_per_client=256,
            buckets=((side, side),), max_buckets=2,
            warm_buckets=((side, side),), replicas=2, model_version="v0")
        service = MatchService(cfg, params_old, scfg).start()
        futs = []
        min_ready = None
        try:
            rcfg = RolloutConfig(
                canary_fraction=0.5, canary_min_results=4,
                canary_timeout_s=300.0, drain_timeout_s=120.0,
                state_path=state_path)
            from ncnet_tpu.serving import Overloaded

            # ONE repeated pair: the canary judge compares old-vs-new
            # quality distributions over the judge window, and with a
            # handful of canary samples per-INPUT variation across
            # distinct pairs reads as model drift — identical inputs make
            # the PSI verdict measure the model delta alone
            p0 = pair()
            t0 = time.perf_counter()
            ctl = service.start_rollout(cand, config=rcfg)
            shed_at_submit = 0
            while True:
                st = ctl.status()
                if st["phase"] in ("COMPLETE", "ROLLED_BACK", "IDLE"):
                    break
                if time.perf_counter() - t0 > 600:
                    break
                # the stream offers load faster than a tiny CPU engine
                # absorbs it: elastic admission shedding the overflow IS
                # the designed behavior — classify it, keep streaming
                try:
                    futs.append(service.submit(*p0))
                except Overloaded as e:
                    shed_at_submit += 1
                    time.sleep(min(e.retry_after_s or 0.1, 0.5))
                pool = (service.health().get("pool") or {})
                if pool.get("ready") is not None:
                    min_ready = pool["ready"] if min_ready is None \
                        else min(min_ready, pool["ready"])
                time.sleep(0.02)
            rollout_wall = time.perf_counter() - t0
            outcomes = {"result": 0, "other": 0,
                        "shed_at_submit": shed_at_submit}
            walls = []
            for f in futs:
                try:
                    walls.append(f.result(timeout=600).wall_s * 1e3)
                    outcomes["result"] += 1
                except Exception:  # noqa: BLE001 — classified accounting
                    outcomes["other"] += 1
            snap = service.metrics()
            out.update({
                "phase": st["phase"],
                "verdict": st.get("verdict"),
                "old_version": st.get("old_version"),
                "new_version": st.get("new_version"),
                "rollout_wall_s": round(rollout_wall, 2),
                "streamed": len(futs),
                "outcomes": outcomes,
                "lost": sum(1 for f in futs if f.outcome is None),
                "min_ready_replicas": min_ready,
                "stream_latency_ms": _percentiles(walls),
                "results_by_version": {
                    k[len("version_results_"):]: v
                    for k, v in snap.items()
                    if k.startswith("version_results_")},
                "resolved_checkpoint": resolve_serving_checkpoint(
                    state_path, "(old)"),
                "pod_version": service.model_version,
            })
        finally:
            service.stop()
    return out


def _structured_pano(i: int, hw=(96, 128)):
    """Deterministic STRUCTURED test image: distinct per-pano hue levels +
    a stripe pattern.  Random-noise images are useless here — the raw
    statistics extractor scores them all ~identical (cosine ~0.9999), so a
    noise-built fixture cannot prove the shortlist ranks correctly."""
    import numpy as np

    img = np.zeros((*hw, 3), np.uint8)
    img[..., 0] = (37 * i) % 256
    img[..., 1] = (91 * i + 13) % 256
    img[:: (i % 5) + 2, :, 2] = 255
    return img


def build_coarse_fixture(root: str, n_panos: int, factor: int = 4,
                         grid: int = 16):
    """Synthetic raw-extractor coarse store + index under ``root`` (the
    retrieval analog of the router phase's FakeEngine backends: numpy only,
    zero compiles).  Returns ``(index_path, {name: image})``."""
    from ncnet_tpu.retrieval.index import write_index_manifest
    from ncnet_tpu.retrieval.scoring import raw_coarse_volume
    from ncnet_tpu.store import (
        FeatureStore,
        coarse_fingerprint,
        content_digest,
    )

    fp = coarse_fingerprint(f"raw-s{grid}-k0-f32", factor)
    store = FeatureStore(root, fp, scope="probe_fixture")
    panos, images = {}, {}
    try:
        for i in range(n_panos):
            img = _structured_pano(i)
            name = f"pano{i:03d}.jpg"
            digest = content_digest(img)
            store.resolve(
                digest,
                lambda img=img: raw_coarse_volume(img, factor, grid=grid))
            panos[name] = digest
            images[name] = img
    finally:
        store.close()
    index_path = os.path.join(root, "coarse_index.shard0_of_1.json")
    write_index_manifest(index_path, fingerprint=fp, factor=factor,
                         extractor="raw", panos=panos)
    return index_path, images


def spawn_shards(n: int, store_root: str, index_path: str,
                 replication: int):
    """Spawn ``n`` serve_shard subprocesses over one shared coarse store +
    index and block for their startup lines.  Returns ``[(Popen, url)]``;
    caller owns teardown (:func:`stop_backends` works unchanged)."""
    import subprocess

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "serve_shard.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               NCNET_TPU_PERF_STORE="off", NCNET_TPU_TIER_CACHE="off")
    shard_ids = ",".join(f"s{i}" for i in range(n))
    procs = []
    for i in range(n):
        procs.append(subprocess.Popen(
            [sys.executable, script, "--shard-id", f"s{i}",
             "--shards", shard_ids, "--store", store_root,
             "--index", index_path, "--replication", str(replication)],
            stdout=subprocess.PIPE, text=True, env=env))
    out = []
    try:
        for p in procs:
            line = p.stdout.readline()
            doc = json.loads(line) if line.strip() else {}
            if "url" not in doc:
                raise RuntimeError(f"shard failed to start: {doc}")
            out.append((p, doc["url"]))
    except Exception:
        for p in procs:
            p.kill()
        raise
    return out


def probe_shards(n_shards: int, n_panos: int, n_queries: int,
                 replication: int = 2) -> Dict[str, Any]:
    """The retrieval-tier sweep: scatter-gather walls + coverage through a
    real ``RetrievalCoordinator`` over ``n_shards`` spawned shard hosts —
    steady state, then a SIGKILLed shard mid-sweep (replication turning
    shard death into lost capacity, not lost coverage)."""
    import tempfile

    import numpy as np

    from ncnet_tpu.retrieval import RetrievalConfig, RetrievalCoordinator
    from ncnet_tpu.retrieval.index import load_index_manifests
    from ncnet_tpu.retrieval.scoring import (
        pooled_descriptor,
        raw_coarse_volume,
    )

    out: Dict[str, Any] = {"shards": n_shards, "panos": n_panos,
                           "replication": replication,
                           "n_queries": n_queries}
    with tempfile.TemporaryDirectory() as root:
        index_path, images = build_coarse_fixture(root, n_panos)
        index = load_index_manifests(index_path)
        names = list(images)
        procs = spawn_shards(n_shards, root, index_path, replication)
        coord = None
        try:
            coord = RetrievalCoordinator(
                {f"s{i}": url for i, (_, url) in enumerate(procs)},
                list(index["panos"]),
                RetrievalConfig(replication=replication, topk=5,
                                probe_period_s=0.3, resurrect_after_s=0.3))
            coord.start()

            def query(i):
                img = images[names[i % len(names)]]
                desc = pooled_descriptor(
                    raw_coarse_volume(img, index["factor"], grid=16))
                return coord.retrieve(desc, budget_s=10.0,
                                      request_id=f"probe-{i}")

            def sweep(n):
                walls, covs, hedges = [], [], 0
                outcomes = {"result": 0, "degraded": 0, "deadline": 0,
                            "shed": 0}
                t0 = time.perf_counter()
                for i in range(n):
                    try:
                        ans = query(i)
                    except Exception as e:  # noqa: BLE001 — classified
                        kind = type(e).__name__
                        outcomes["deadline" if "Deadline" in kind
                                 else "shed"] += 1
                        continue
                    outcomes["degraded" if ans["degraded"]
                             else "result"] += 1
                    walls.append(ans["wall_ms"])
                    covs.append(ans["coverage"])
                    hedges += ans["hedges"]
                span = time.perf_counter() - t0
                return {
                    "outcomes": outcomes,
                    "qps": round(n / span, 2),
                    "latency_ms": _percentiles(walls),
                    "coverage_pct": round(
                        100.0 * float(np.mean(covs)), 2) if covs else 0.0,
                    "coverage_min": round(
                        float(np.min(covs)), 6) if covs else 0.0,
                    "hedge_pct": round(100.0 * hedges / max(1, n), 2),
                }

            # 1. steady-state scatter-gather walls
            out["steady"] = sweep(n_queries)

            # 2. SIGKILL one shard mid-sweep: with R-way replication every
            # query must still terminate classified at full coverage
            victim_proc, victim_url = procs[0]
            victim_proc.kill()  # SIGKILL: no drain, no goodbye
            out["failover"] = sweep(n_queries)
            out["failover"]["killed"] = victim_url
            out["health"] = coord.health()
        finally:
            if coord is not None:
                coord.stop()
            stop_backends(procs)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Probe the resident match service on the attached "
                    "device (batching walls, demotion under load, shed "
                    "behavior)")
    ap.add_argument("--sides", default="400",
                    help="comma-separated square bucket sides (default 400)")
    ap.add_argument("--pairs", type=int, default=48,
                    help="closed-loop pairs per bucket (default 48)")
    ap.add_argument("--tiny", action="store_true",
                    help="CPU-sized smoke config (tiny backbone, 64 px)")
    ap.add_argument("--no-demote", action="store_true",
                    help="skip the injected-failure demotion measurement")
    ap.add_argument("--burst-factor", type=float, default=3.0,
                    help="overload burst rate as a multiple of capacity")
    ap.add_argument("--replicas", default="1",
                    help="comma-separated pool sizes for the scaling sweep "
                         "(default 1 = no sweep); run on a multi-chip host "
                         "with one replica per visible device — e.g. "
                         "--replicas 1,2,4 on a v5e-4")
    ap.add_argument("--router", type=int, default=0,
                    help="spawn N backend subprocesses (CPU-forced) behind "
                         "a MatchRouter and sweep the POD tier instead of "
                         "the local service: capacity through the router, "
                         "the SIGKILL failover pause + zero-lost "
                         "accounting, and the shed wall")
    ap.add_argument("--rollout", action="store_true",
                    help="sweep the LIVE-ROLLOUT plane instead: save a "
                         "candidate checkpoint, drive a sustained stream "
                         "against a 2-replica pool while a canaried "
                         "old->new weight swap promotes through it, and "
                         "report the rollout wall, the mixed-version "
                         "stream latency, per-version accounting, and the "
                         "zero-lost verdict (--tiny = tier-1 smoke)")
    ap.add_argument("--shards", type=int, default=0,
                    help="spawn N retrieval shard subprocesses over a "
                         "synthetic coarse index and sweep the RETRIEVAL "
                         "tier instead: scatter-gather walls + coverage, "
                         "then the SIGKILL failover sweep; records "
                         "retrieve_p95_ms / retrieve_coverage_pct / "
                         "retrieve_hedge_pct to the perf store")
    ap.add_argument("--shard-panos", type=int, default=24,
                    help="panos in the synthetic retrieval fixture")
    ap.add_argument("--shard-queries", type=int, default=24,
                    help="queries per retrieval sweep phase")
    ap.add_argument("--replication", type=int, default=2,
                    help="replica count for the --shards sweep")
    ap.add_argument("--keep-logs", action="store_true",
                    help="(--router mode) give each spawned backend its "
                         "own --events log in a directory that survives "
                         "the probe, and name the paths in the report — "
                         "the inputs tools/trace_export.py --federate "
                         "merges into one pod trace")
    ap.add_argument("--json", default=None, help="also write the JSON here")
    args = ap.parse_args(argv)

    # stdout is the probe's one JSON document; the injected-failure phase
    # legitimately logs recovery warnings through the library console sink
    # (also stdout), so quiet it to errors FOR THE PROBE RUN ONLY unless
    # the operator overrode the level themselves — restored afterwards, so
    # an in-process caller (the tier-1 smoke test) does not inherit a
    # silenced logger
    level_was_unset = "NCNET_TPU_LOG_LEVEL" not in os.environ
    os.environ.setdefault("NCNET_TPU_LOG_LEVEL", "error")
    try:
        sides = [int(s) for s in args.sides.split(",") if s]
        replicas = [int(r) for r in args.replicas.split(",") if r] or [1]
        if args.shards > 0:
            ret = probe_shards(args.shards, args.shard_panos,
                               args.shard_queries,
                               replication=args.replication)
            out = {"retrieval": ret}
            # the perf-store families perf_regress --check gates: the p95
            # scatter-gather wall (lower), mean steady coverage (higher —
            # see perfstore._HIGHER_TOKENS), and the steady hedge rate
            # (lower: hedges firing with no straggler is paid redundancy)
            from ncnet_tpu.observability.perfstore import maybe_record

            steady = ret.get("steady", {})
            lat = steady.get("latency_ms") or {}
            metrics = {}
            if lat.get("p95") is not None:
                metrics["retrieve_p95_ms"] = lat["p95"]
            if steady:
                metrics["retrieve_coverage_pct"] = steady["coverage_pct"]
                metrics["retrieve_hedge_pct"] = steady["hedge_pct"]
            maybe_record(metrics, source="serve_probe_shards")
        elif args.rollout:
            out = {"rollout": probe_rollout(sides[0], args.pairs,
                                            args.tiny)}
        elif args.router > 0:
            out = {"router": probe_router(
                args.router, sides[0], args.pairs, args.burst_factor,
                args.tiny, keep_logs=args.keep_logs)}
        else:
            out = probe(sides, args.pairs, args.tiny, not args.no_demote,
                        args.burst_factor, replicas=replicas)
    finally:
        if level_was_unset:
            os.environ.pop("NCNET_TPU_LOG_LEVEL", None)
    doc = json.dumps(out, indent=2, sort_keys=True)
    sys.stdout.write(doc + "\n")
    if args.json:
        with open(args.json, "w") as f:
            f.write(doc + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
