#!/usr/bin/env python
"""Coarse-to-fine sparse correlation probe: per-k compile/memory/wall
characterization of the sparse pipeline against the dense filter.

The coarse2fine tier's acceptance rides on the PR 13 memory ledger
(``mem_filter_temp_bytes_sparse`` < dense at the same shape) and the perf
store's wall series — both need MEASURED numbers from a real device.  This
probe produces them for the next TPU-attached session:

  * for each requested ``k``: AOT-compile the full sparse filter program
    (coarse pass + top-k + gathered fine refinement) at the given feature
    shape, record its ``memory_analysis()`` row into the compiled-program
    memory ledger (program ``sparse_corr_probe``, keyed per k), and report
    temp/peak bytes beside the dense filter program's at the same shape;
  * the Pallas gather-into-VMEM tier's feasibility verdict and (on TPU) its
    real-compile probe outcome per shape class — the gather-ring VMEM
    accounting of ``ops/sparse_corr.sparse_gather_feasible``;
  * with ``--time`` (TPU session): steady-state walls, sparse vs dense.

``--tiny`` is the CPU smoke kept tier-1 (tests/test_sparse_corr.py): a
miniature shape through every rung that works without Mosaic — XLA tile
gather vs the interpret-mode Pallas gather kernel (bitwise), k=full vs
dense volume parity, a recall-vs-k curve, and the AOT memory accounting
path (fail-open where the backend lacks ``memory_analysis``).

Usage::

    python tools/sparse_corr_probe.py --k 1,2,4,8 --size 50 [--time]
    python tools/sparse_corr_probe.py --tiny
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

_out = sys.stdout.write
_err = sys.stderr.write


def _params_for(kernels, channels, key_seed=1):
    import jax

    from ncnet_tpu.ops import conv4d_init

    key = jax.random.key(key_seed)
    nc = []
    c_in = 1
    for k, c_out in zip(kernels, channels):
        key, sub = jax.random.split(key)
        w, b = conv4d_init(sub, k, c_in, c_out)
        nc.append({"w": w, "b": b})
        c_in = c_out
    return {"nc": nc}


def _aot_memory(fn, *sds):
    """(compiled, analysis-dict|None) — the analysis is fail-open (CPU
    backends may lack memory_analysis)."""
    import jax

    from ncnet_tpu.observability import memory as obs_memory

    compiled = jax.jit(fn).lower(*sds).compile()
    return compiled, (obs_memory.analysis_dict(compiled) or None)


def probe(args) -> int:
    import jax
    import jax.numpy as jnp

    from ncnet_tpu.config import ModelConfig
    from ncnet_tpu.models.ncnet import coarse2fine_filter, ncnet_filter
    from ncnet_tpu.observability import memory as obs_memory
    from ncnet_tpu.ops import correlation_4d
    from ncnet_tpu.ops.sparse_corr import sparse_gather_feasible
    from ncnet_tpu.ops.sparse_topk import patch_side, resolve_halo

    kernels = tuple(int(v) for v in args.kernels.split(","))
    channels = tuple(int(v) for v in args.channels.split(","))
    ks = [int(v) for v in args.k.split(",")]
    s, c_dim, b = args.size, args.c, args.batch
    halo = resolve_halo(args.halo, args.factor)
    patch = patch_side(args.factor, halo)
    dt = jnp.bfloat16 if args.bf16 else jnp.float32
    params = _params_for(kernels, channels)
    sds = jax.ShapeDtypeStruct((b, s, s, c_dim), dt)
    report = {
        "size": s, "channels": c_dim, "batch": b, "factor": args.factor,
        "halo": halo, "patch": patch, "dtype": jnp.dtype(dt).name,
        "device_kind": jax.devices()[0].device_kind,
        "gather_vmem_feasible": sparse_gather_feasible(
            s, s, c_dim, patch, args.factor, halo,
            itemsize=jnp.dtype(dt).itemsize),
        "k": {},
    }

    cfg = ModelConfig(ncons_kernel_sizes=kernels, ncons_channels=channels,
                      half_precision=args.bf16, sparse_factor=args.factor,
                      sparse_halo=args.halo)

    def dense_fn(p, fa, fb):
        return ncnet_filter(cfg, p, correlation_4d(fa, fb)).corr

    try:
        _, dense_mem = _aot_memory(dense_fn, params, sds, sds)
        report["dense"] = dense_mem
    except Exception as e:  # the dense volume may simply not compile/fit
        report["dense"] = {"error": str(e)[:200]}
        dense_mem = None

    for k in ks:
        cfg_k = cfg.replace(sparse_topk=k)

        def sparse_fn(p, fa, fb, cfg_k=cfg_k):
            return coarse2fine_filter(cfg_k, p, fa, fb).corr

        row = {}
        try:
            compiled, mem = _aot_memory(sparse_fn, params, sds, sds)
            row["memory"] = mem
            obs_memory.record_program(
                "sparse_corr_probe", f"{s}x{s}x{c_dim}xb{b}|k={k}|p={patch}",
                analysis=compiled, tier="coarse2fine", source="probe")
            if dense_mem and mem and mem.get("temp_bytes") \
                    and dense_mem.get("temp_bytes"):
                row["temp_vs_dense"] = round(
                    mem["temp_bytes"] / dense_mem["temp_bytes"], 4)
        except Exception as e:
            row["error"] = str(e)[:300]
        report["k"][k] = row

    if args.time:
        import time as _time

        import numpy as np

        def wall(fn):
            rng = np.random.default_rng(0)
            fa = jnp.asarray(rng.normal(size=(b, s, s, c_dim)) * 0.05, dt)
            fb = jnp.asarray(rng.normal(size=(b, s, s, c_dim)) * 0.05, dt)
            jitted = jax.jit(fn)
            jax.block_until_ready(jitted(params, fa, fb))  # compile
            walls = []
            for _ in range(args.reps):
                t0 = _time.perf_counter()
                jax.block_until_ready(jitted(params, fa, fb))
                walls.append((_time.perf_counter() - t0) * 1e3)
            return float(np.median(walls))

        try:
            report["dense_wall_ms"] = round(wall(dense_fn), 3)
        except Exception as e:
            report["dense_wall_ms"] = None
            _err(f"dense wall failed: {str(e)[:200]}\n")
        for k in ks:
            cfg_k = cfg.replace(sparse_topk=k)
            try:
                report["k"][k]["wall_ms"] = round(wall(
                    lambda p, fa, fb, cfg_k=cfg_k:
                    coarse2fine_filter(cfg_k, p, fa, fb).corr), 3)
            except Exception as e:
                _err(f"sparse wall k={k} failed: {str(e)[:200]}\n")

    _out(json.dumps(report, indent=2, sort_keys=True, default=str) + "\n")
    return 0


def tiny(args) -> int:
    """CPU smoke: every Mosaic-free rung of the sparse pipeline at a
    miniature shape.  Exit nonzero on any parity failure — this is the
    tier-1 guard that keeps the probe runnable for the TPU session."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ncnet_tpu.config import ModelConfig
    from ncnet_tpu.models.ncnet import ncnet_filter, ncnet_match_volume
    from ncnet_tpu.ops import candidate_recall, correlation_4d, \
        feature_l2_norm, pool_features, topk_candidates
    from ncnet_tpu.ops.sparse_corr import (
        gather_source_patches,
        gather_tile_corr_pallas,
        source_patch_index,
        sparse_fine_corr,
    )
    from ncnet_tpu.ops.sparse_topk import candidate_origins, patch_side

    rng = np.random.default_rng(7)
    b, s, c_dim, factor, halo = 1, 8, 16, 2, 2
    patch = patch_side(factor, halo)
    fa = feature_l2_norm(jnp.asarray(
        rng.normal(size=(b, s, s, c_dim)).astype(np.float32)))
    fb = feature_l2_norm(jnp.asarray(
        rng.normal(size=(b, s, s, c_dim)).astype(np.float32)))
    cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3, 3),
                      ncons_channels=(4, 1))
    params = _params_for(cfg.ncons_kernel_sizes, cfg.ncons_channels)
    n_cells = (s // factor) ** 2

    # 1) XLA gather tier vs interpret-mode Pallas gather kernel: bitwise
    cand = jnp.asarray(
        rng.integers(0, n_cells, (b, n_cells, 3)).astype(np.int32))
    tiles = sparse_fine_corr(fa, fb, cand, factor=factor, halo=halo)
    ia, ja = source_patch_index(s, s, factor, patch)
    oi, oj = candidate_origins(cand, s // factor, factor, patch, s, s)
    fa_p2 = gather_source_patches(fa, ia, ja).reshape(
        b, n_cells, patch * patch, c_dim)
    v_pl = gather_tile_corr_pallas(
        fa_p2, fb, oi // factor, oj, patch=patch, factor=factor,
        interpret=True,
    ).reshape(tiles.values.shape)
    d = float(jnp.max(jnp.abs(v_pl - tiles.values)))
    _out(f"gather kernel (interpret) vs XLA tier: max|diff| = {d}\n")
    if d != 0.0:
        _err("FAIL: gather tiers disagree\n")
        return 1

    # 2) k = full coverage reproduces the dense filtered volume
    dense = ncnet_filter(cfg, params, correlation_4d(fa, fb)).corr
    sparse = ncnet_match_volume(
        cfg.replace(sparse_topk=n_cells, sparse_factor=factor,
                    sparse_halo=halo), params, fa, fb).corr
    d = float(jnp.max(jnp.abs(dense - sparse)))
    _out(f"k=full sparse vs dense volume: max|diff| = {d}\n")
    if not np.allclose(np.asarray(dense), np.asarray(sparse),
                       atol=1e-5, rtol=1e-4):
        _err("FAIL: k=full does not reproduce the dense volume\n")
        return 1

    # 3) recall-vs-k curve is monotone to 1.0
    coarse = ncnet_filter(
        cfg, params,
        correlation_4d(pool_features(fa, factor), pool_features(fb, factor))
    ).corr
    raw = np.asarray(correlation_4d(fa, fb))
    recalls = [candidate_recall(
        np.asarray(topk_candidates(coarse, k)), raw, factor)
        for k in (1, 4, n_cells)]
    _out(f"recall @ k=1,4,full: {[round(r, 3) for r in recalls]}\n")
    if recalls[-1] != 1.0 or any(
            recalls[i] > recalls[i + 1] + 1e-9 for i in range(2)):
        _err("FAIL: recall curve not monotone to 1.0\n")
        return 1

    # 4) AOT memory accounting path (fail-open off-TPU)
    from ncnet_tpu.models.ncnet import coarse2fine_filter

    cfg_k = cfg.replace(sparse_topk=2, sparse_factor=factor,
                        sparse_halo=halo)
    sds = jax.ShapeDtypeStruct((b, s, s, c_dim), jnp.float32)
    _, mem = _aot_memory(
        lambda p, x, y: coarse2fine_filter(cfg_k, p, x, y).corr,
        params, sds, sds)
    _out(f"sparse AOT memory analysis: "
         f"{'unavailable on this backend' if mem is None else mem}\n")
    _out("tiny smoke: OK\n")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Per-k compile/memory/wall probe of the coarse-to-fine "
                    "sparse correlation pipeline")
    ap.add_argument("--k", default="1,2,4,8",
                    help="comma-separated candidate counts to probe")
    ap.add_argument("--size", type=int, default=50,
                    help="fine feature grid side (50 = 2x the PF-Pascal "
                         "bench grid)")
    ap.add_argument("--c", type=int, default=256,
                    help="feature channels (1024 = resnet101 layer3)")
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--factor", type=int, default=2)
    ap.add_argument("--halo", type=int, default=-1,
                    help="-1 = auto (one coarse ring)")
    ap.add_argument("--kernels", default="5,5,5")
    ap.add_argument("--channels", default="16,16,1")
    ap.add_argument("--bf16", action="store_true", default=True)
    ap.add_argument("--no-bf16", dest="bf16", action="store_false")
    ap.add_argument("--time", action="store_true",
                    help="measure steady-state walls (TPU session)")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--tiny", action="store_true",
                    help="CPU smoke: miniature parity/recall/memory pass "
                         "(tier-1)")
    args = ap.parse_args(argv)
    if args.tiny:
        return tiny(args)
    return probe(args)


if __name__ == "__main__":
    raise SystemExit(main())
