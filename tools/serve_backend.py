#!/usr/bin/env python
"""Run ONE per-host match backend: a resident ``MatchService`` behind the
introspection server's ``/healthz``/``/metrics`` control plane and
``POST /match`` wire data plane (``ncnet_tpu/serving/wire.py``).

This is the process a ``serving/router.py::MatchRouter`` fans out to — and
the process the multi-host chaos suite (tests/test_router.py) SIGKILLs,
restarts, and drains.  Lifecycle contract:

  * on start it prints exactly ONE JSON line to stdout —
    ``{"url": "http://host:port", "pid": ...}`` — and nothing else
    (spawners block on that line to learn the ephemeral port);
  * SIGTERM begins the coordinated drain: the service finishes admitted
    work while its ``/healthz`` answers 503, so the fronting router
    demotes this host out of routing BEFORE the drain completes; the
    process exits 0 once STOPPED;
  * a fixed ``--port`` supports the restart-in-place shape (a supervisor
    reviving a killed host at the same address, which the router's
    resurrection probes then re-admit).

Engines: ``--tiny`` builds the real tiny-backbone model (CPU-honest walls,
pays one small compile); ``--fake-engine`` substitutes the chaos suite's
deterministic fake device (``--latency`` per batch) so process-level fault
tests run with zero compiles.  ``--events`` binds the host's own event log
(torn-tail tolerant, so a SIGKILLed host's log still replays).

Usage::

    python tools/serve_backend.py [--port 0] [--host 127.0.0.1]
        [--tiny | --fake-engine] [--replicas N] [--latency 0.02]
        [--max-queue 64] [--max-batch 4] [--events events.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


class FakeEngine:
    """The chaos suite's device stand-in (tests/test_serving_pool.py
    protocol): real service/replica code paths, no jit compiles — what the
    process-kill chaos chain runs so spawning 3 hosts costs milliseconds,
    not compiles."""

    half_precision = False

    def __init__(self, latency_s: float = 0.02):
        self.latency_s = latency_s

    @staticmethod
    def split(table):
        from ncnet_tpu.serving import BatchMatchEngine

        return BatchMatchEngine.split(table)

    def dispatch(self, src, tgt):
        from ncnet_tpu.utils import faults

        faults.device_error_hook("fake_serve")
        return (src.shape[0], time.monotonic())

    def fetch(self, handle):
        import numpy as np

        b, t0 = handle
        while time.monotonic() - t0 < self.latency_s:
            time.sleep(0.005)
        table = np.zeros((b, 6, 16), np.float32)
        table[:, 4, :] = 1.0
        table[:, 5, :5] = [0.5, 0.1, 0.4, 0.9, 0.8]
        return table

    def retrace(self):
        pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="One per-host match backend: MatchService + /healthz "
                    "control plane + /match wire data plane")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="introspection/data-plane port (0 = ephemeral, "
                         "printed in the startup JSON line; fixed for the "
                         "restart-in-place shape)")
    ap.add_argument("--tiny", action="store_true",
                    help="real tiny-backbone engines (CPU-honest walls)")
    ap.add_argument("--fake-engine", action="store_true",
                    help="deterministic fake device (no compiles) — the "
                         "process-level chaos configuration")
    ap.add_argument("--replicas", type=int, default=1,
                    help="engines in this host's pool (fake or real)")
    ap.add_argument("--latency", type=float, default=0.02,
                    help="fake-engine seconds per batch")
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--bucket-side", type=int, default=32,
                    help="square bucket side (fixed single-bucket ladder)")
    ap.add_argument("--events", default=None,
                    help="bind this host's event log here (torn-tail "
                         "tolerant across SIGKILL)")
    args = ap.parse_args(argv)
    if args.tiny == args.fake_engine:
        ap.error("give exactly one of --tiny / --fake-engine")

    from ncnet_tpu.observability import events as obs_events
    from ncnet_tpu.serving import MatchService, ServingConfig

    if args.events:
        from ncnet_tpu.observability import EventLog

        obs_events.set_global_sink(EventLog(args.events))

    side = int(args.bucket_side)
    serving_kw = dict(
        max_queue=args.max_queue, max_batch=args.max_batch,
        max_in_flight_per_client=max(args.max_queue, 64),
        bucket_multiple=side, max_image_side=side,
        buckets=((side, side),), max_buckets=2,
        install_sigterm=True,
        introspect_host=args.host, introspect_port=args.port,
    )
    if args.fake_engine:
        engines = [FakeEngine(latency_s=args.latency)
                   for _ in range(max(1, args.replicas))]
        service = MatchService(engine=engines,
                               serving=ServingConfig(**serving_kw))
    else:
        import warnings

        import jax

        from ncnet_tpu import models
        from ncnet_tpu.config import ModelConfig

        cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                          ncons_channels=(1,), half_precision=False)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # random trunk: serving only
            params = models.init_ncnet(cfg, jax.random.key(0))
        service = MatchService(cfg, params, ServingConfig(
            **serving_kw, replicas=max(1, args.replicas),
            warm_buckets=((side, side),)))

    service.start()
    if service.introspect_url is None:
        print(json.dumps({"error": f"failed to bind {args.host}:"
                          f"{args.port}"}), flush=True)
        service.stop()
        return 1
    print(json.dumps({"url": service.introspect_url, "pid": os.getpid()}),
          flush=True)
    # serve until a drain (SIGTERM via the service's handler, or a stop()
    # from another thread) runs to completion; the poll keeps the main
    # thread interruptible for the signal handler
    try:
        while service.state != "STOPPED":
            time.sleep(0.1)
            if service.state == "DRAINING":
                # join the worker's drain so exit is clean and ordered
                service.stop()
    except KeyboardInterrupt:
        service.stop(drain=False)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
