#!/usr/bin/env python
"""Sweep per-layer (dx, dw) gradient-formulation routings COMPOSED in the
symmetric NC stack (value_and_grad w.r.t. params AND the volume — the
training chain).

Round-3 measured only a GLOBAL dw choice (custom ~= plain); the grad-split
probe (tools/nc_grad_split_probe.py, bf16 bs8) shows dx ~= 50 ms and
dw ~= 50 ms per application vs ~23 ms of forward — both ~2x their FLOP
cost — so this probe hunts a better routing per layer.

Usage: python tools/vjp_sweep_probe.py [batch] [dtype] [spec ...]
  spec: name=dx0/dw0,dx1/dw1,dx2/dw2   ('-' = plain AD for that layer)
  default: plain, all-custom-default, dx sweeps, dw sweeps
"""

import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")

from _timing import timeit  # noqa: E402

from ncnet_tpu.models.ncnet import neigh_consensus  # noqa: E402
from ncnet_tpu.ops import conv4d_init, correlation_4d  # noqa: E402
from ncnet_tpu.ops.norm import feature_l2_norm  # noqa: E402

B = int(sys.argv[1]) if len(sys.argv) > 1 else 8
DT = jnp.bfloat16 if (len(sys.argv) > 2 and sys.argv[2] == "bf16") else jnp.float32
S, C = 25, 1024


def parse_spec(s):
    out = []
    for part in s.split(","):
        if part == "-":
            out.append(None)
        else:
            dx, dw = part.split("/")
            out.append({"dx": dx, "dw": dw})
    return out


SWEEP = []
for arg in sys.argv[3:]:
    name, spec = arg.split("=")
    SWEEP.append((name, parse_spec(spec)))
if not SWEEP:
    SWEEP = [
        ("plain", None),
        ("custom_def", [{"dx": "auto", "dw": "coutfold"}] * 3),
        ("dx_unroll", [{"dx": "unroll", "dw": "coutfold"}] * 3),
        ("dx_tapfold", [{"dx": "tapfold", "dw": "coutfold"}] * 3),
        ("dw_unroll", [{"dx": "auto", "dw": "unroll"}] * 3),
        ("dw_tapfold", [{"dx": "auto", "dw": "tapfold"}] * 3),
    ]


def main():
    ks = jax.random.split(jax.random.key(7), 3)
    chans = [(1, 16), (16, 16), (16, 1)]
    params0 = [
        dict(zip(("w", "b"), conv4d_init(k, 5, ci, co)))
        for k, (ci, co) in zip(ks, chans)
    ]

    for name, routing in SWEEP:
        cg = False if routing is None else routing

        def loss(params, corr, _cg=cg):
            params = jax.tree.map(lambda x: x.astype(DT), params)
            out = neigh_consensus(params, corr, symmetric=True, custom_grad=_cg)
            return jnp.mean(out.astype(jnp.float32))

        def tick(carry, _loss=loss):
            fa, fb, params = carry
            corr = correlation_4d(fa, fb).astype(DT)
            val, (gp, gc) = jax.value_and_grad(_loss, argnums=(0, 1))(params, corr)
            fa = fa + (val * 1e-9 + jnp.sum(gc.astype(jnp.float32)) * 1e-12
                       ).astype(fa.dtype)
            params = jax.tree.map(
                lambda p, gg: p + (jnp.sum(gg.astype(jnp.float32)) * 1e-12
                                   ).astype(p.dtype), params, gp)
            return (fa, fb, params)

        def make_input(key):
            k1, k2 = jax.random.split(key)
            fa = feature_l2_norm(jax.random.normal(k1, (B, S, S, C), jnp.float32))
            fb = feature_l2_norm(jax.random.normal(k2, (B, S, S, C), jnp.float32))
            return (fa, fb, params0)

        try:
            ms = timeit(tick, make_input, n_long=4, reps=3)
            print(f"{name:14s} {ms:8.1f} ms/step  {ms / B:6.2f} ms/pair",
                  flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"{name:14s} FAILED: {str(e)[:200]}", flush=True)


if __name__ == "__main__":
    main()
