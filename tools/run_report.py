#!/usr/bin/env python
"""Replay one or more ncnet_tpu event logs into a run report.

The event log (``ncnet_tpu/observability/events.py``) is the durable,
machine-readable trace of a run: step/epoch boundaries, checkpoint commits,
NaN-guard skips, tier selections/demotions, retries, quarantines, watchdog
timeouts, metrics flushes.  This tool turns one or more of those JSONL files
(a resumed run appends to the same file; sharded runs write several) into
the report an operator actually wants after a run ends — or dies:

  * run/resume lineage (every run id in the file, with its envelope);
  * step-time percentiles + throughput + the MFU trajectory;
  * the tier timeline (selections and demotions, in order);
  * failure accounting: NaN skips, retries by kind, quarantines, watchdog
    timeouts, preemptions;
  * a divergence postmortem when the run died of TrainDivergedError (the
    last N steps before the fatal streak, with losses and grad norms);
  * checkpoint/resume consistency (commits seen, resume positions).

Usage::

    python tools/run_report.py <events.jsonl> [more.jsonl ...] [--json]

``--json`` emits the raw report dict (one JSON document) instead of text.
Replay is torn-tail tolerant: a log whose writer was SIGKILLed mid-append
still replays in full minus at most the torn trailing line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ncnet_tpu.observability.events import replay_events  # noqa: E402


def _percentiles(xs: List[float], qs=(50, 90, 99)) -> Dict[str, float]:
    if not xs:
        return {}
    xs = sorted(xs)
    out: Dict[str, float] = {}
    for q in qs:
        # nearest-rank on the sorted walls: no numpy needed, exact enough
        # for a report
        i = min(len(xs) - 1, max(0, round(q / 100 * (len(xs) - 1))))
        out[f"p{q}"] = xs[i]
    out["mean"] = sum(xs) / len(xs)
    out["n"] = len(xs)
    return out


def build_span_breakdown(events: List[dict]) -> Dict[str, Any]:
    """Critical-path accounting over the span events: per span NAME (keyed
    under its parent's name, so `train_step > dispatch` and a root-level
    `dispatch` stay distinct), the count, total wall, and SELF time — total
    minus the time spent inside child spans — which is what actually ranks
    phases on the critical path: a `train_step` span's total wall double-
    counts every phase nested in it, its self time is the unattributed
    remainder.  Unclosed spans (SIGKILL mid-span) are counted, not timed."""
    # pair B/E by (run, span id); resolve each span's parent NAME via the
    # parent id stamped on the B event
    opens: Dict[tuple, dict] = {}
    closed: List[dict] = []
    unclosed = 0
    names: Dict[tuple, str] = {}
    child_time: Dict[tuple, float] = {}
    for e in events:
        if e.get("event") != "span":
            continue
        key = (e.get("run"), e.get("span"))
        if e.get("ph") == "B":
            opens[key] = e
            names[key] = str(e.get("name"))
        elif e.get("ph") == "E":
            b = opens.pop(key, None)
            if b is None:
                continue
            dur = e.get("dur_s")
            if not isinstance(dur, (int, float)):
                continue
            parent_key = (e.get("run"), b.get("parent"))
            child_time[parent_key] = child_time.get(parent_key, 0.0) + dur
            closed.append({"key": key, "name": names[key],
                           "parent": b.get("parent"), "dur": float(dur),
                           "error": e.get("error")})
    unclosed = len(opens)
    groups: Dict[tuple, Dict[str, Any]] = {}
    for s in closed:
        parent_name = (names.get((s["key"][0], s["parent"]), "-")
                       if s["parent"] is not None else "-")
        g = groups.setdefault((parent_name, s["name"]), {
            "parent": parent_name, "name": s["name"], "n": 0,
            "total_s": 0.0, "self_s": 0.0, "errors": 0,
        })
        g["n"] += 1
        g["total_s"] += s["dur"]
        g["self_s"] += s["dur"] - child_time.get(s["key"], 0.0)
        if s["error"]:
            g["errors"] += 1
    out = sorted(groups.values(), key=lambda g: -g["self_s"])
    for g in out:
        g["total_s"] = round(g["total_s"], 6)
        g["self_s"] = round(g["self_s"], 6)
        g["mean_s"] = round(g["total_s"] / g["n"], 6)
    return {"groups": out, "closed": len(closed), "unclosed": unclosed}


def build_quality_section(events: List[dict],
                          device_kind: Optional[str],
                          ref_path: Optional[str] = None) -> Dict[str, Any]:
    """Aggregate the ``quality`` events (observability/quality.py): a
    per-(tier, signal) stats table, the signal-vs-PCK rank correlation where
    labels rode along (the PF-Pascal eval emits them side by side — a
    positive rho validates the signal as a label-free PCK proxy), and —
    when a reference file is given/committed — the PSI drift verdicts the
    standalone ``tools/quality_drift.py`` gate would report."""
    from ncnet_tpu.observability.quality import (
        check_drift,
        digests_from_events,
        load_reference,
        reference_binning,
        signal_pck_correlation,
    )

    # ONE aggregation pass serves both the stats table and the drift
    # verdicts: when a reference exists its binning is applied up front
    # (count/mean are binning-independent; table percentiles are ±bin
    # width either way)
    reference = {}
    bins_like = None
    if ref_path and os.path.exists(ref_path):
        reference = load_reference(ref_path)
        if reference:
            bins_like = reference_binning(reference)
    digests = digests_from_events(events, bins_like=bins_like)
    table = []
    for (tier, signal), h in sorted(digests.items()):
        table.append({
            "tier": tier, "signal": signal, "n": h.count,
            "mean": round(h.mean(), 6) if h.count else None,
            "p50": round(h.percentile(50), 6) if h.count else None,
            "p90": round(h.percentile(90), 6) if h.count else None,
        })
    section: Dict[str, Any] = {
        "table": table,
        "pck_spearman": {
            k: (None if v != v else round(v, 4))
            for k, v in signal_pck_correlation(events).items()
        },
    }
    if reference:
        section["drift"] = check_drift(reference, digests,
                                       device_kind=device_kind)
        section["drift_ref"] = ref_path
    return section


def build_slo_section(events: List[dict]) -> Dict[str, Any]:
    """Recompute the SLO error-budget counters from the event log — the
    replay twin of the live tracker (``ncnet_tpu/serving/slo.py``).

    Classification uses the SAME values the live tracker saw: the latency
    objectives stamped into ``serve_start``, the rounded ``wall_ms`` of
    each ``serve_result``, and the ``admitted`` flags on
    ``serve_deadline``/``serve_shed`` — so a complete log replays to
    counters that match the service's final ``/metrics`` scrape EXACTLY
    (the scrape-vs-replay consistency bar).  ``admitted`` here counts
    terminal outcomes of admitted requests, exactly like the tracker: on a
    clean drain it equals the admission count, after a crash it counts
    what actually terminated."""
    cfg: Optional[Dict[str, Any]] = None
    for e in events:
        if e.get("event") == "serve_start" and isinstance(e.get("slo"),
                                                          dict):
            cfg = e["slo"]  # latest service start wins (resume lineage)
    default_ms = cfg.get("default_ms") if cfg else None
    by_bucket = dict((cfg or {}).get("by_bucket") or {})
    budget_pct = float((cfg or {}).get("budget_pct") or 1.0)

    def objective(bucket: str) -> Optional[float]:
        return by_bucket.get(bucket, default_ms)

    bad = {"deadline": 0, "quarantined": 0, "shed": 0, "latency": 0}
    admitted = ok = 0
    for e in events:
        ev = e.get("event")
        if ev == "serve_result":
            admitted += 1
            obj = objective(str(e.get("bucket")))
            wall = e.get("wall_ms")
            if obj is not None and isinstance(wall, (int, float)) \
                    and wall > obj:
                bad["latency"] += 1
            else:
                ok += 1
        elif ev == "serve_deadline" and e.get("admitted") is not False:
            admitted += 1
            bad["deadline"] += 1
        elif ev == "serve_quarantine":
            admitted += 1
            bad["quarantined"] += 1
        elif ev == "serve_shed" and e.get("admitted") is True:
            admitted += 1
            bad["shed"] += 1
    bad_total = sum(bad.values())
    burn = (round(100.0 * (bad_total / admitted) / (budget_pct / 100.0), 4)
            if admitted else 0.0)
    section: Dict[str, Any] = {
        "objectives": cfg,
        "admitted": admitted,
        "ok": ok,
        "bad": bad,
        "bad_total": bad_total,
        "budget_burn_pct": burn,
    }
    slo_events = [e for e in events if e.get("event") == "slo"]
    if slo_events:
        last = slo_events[-1]
        section["slo_events"] = len(slo_events)
        section["final_event"] = {
            k: last.get(k) for k in
            ("admitted", "ok", "bad", "bad_total", "budget_burn_pct",
             "final") if k in last}
        # the consistency verdict itself: does the replay reproduce the
        # tracker's final counters?  (False on a torn log whose terminal
        # events outlived the final slo event, or vice versa.)
        section["matches_final_event"] = all(
            section.get(k) == last.get(k)
            for k in ("admitted", "ok", "bad", "bad_total",
                      "budget_burn_pct"))
    return section


def build_serving_section(events: List[dict]) -> Dict[str, Any]:
    """The serving postmortem: request-outcome accounting (the outcome-total
    invariant ``admitted == results + deadlines + quarantines +
    admitted_sheds``; a nonzero ``unresolved`` means requests died without
    an outcome — the kill-mid-drain signature), per-bucket AND per-replica
    latency/outcome percentiles (the replica-pool postmortem: batches,
    retries, deaths/resurrections per replica id), the queue-depth
    trajectory (from ``serve_batch`` events), and the health-state timeline
    (replica-tagged entries are replica lifecycle edges)."""
    admits = [e for e in events if e.get("event") == "serve_admit"]
    results = [e for e in events if e.get("event") == "serve_result"]
    deadlines = [e for e in events if e.get("event") == "serve_deadline"
                 and e.get("admitted") is not False]
    quarantines = [e for e in events
                   if e.get("event") == "serve_quarantine"]
    sheds = [e for e in events if e.get("event") == "serve_shed"]
    sheds_admitted = [e for e in sheds if e.get("admitted") is True]
    terminals = (len(results) + len(deadlines) + len(quarantines)
                 + len(sheds_admitted))
    # which admitted requests never reached an outcome (lost mid-drain /
    # in flight at process death) — keyed (run, request): request ids
    # restart at r1 per service process, and a restarted run appending to
    # the same log (the resume-lineage design) must not mask a dead run's
    # losses with its own same-named requests
    def _key(e: dict):
        return (e.get("run"), e.get("request"))

    settled_ids = {_key(e) for e in results + quarantines}
    settled_ids |= {_key(e) for e in deadlines}
    settled_ids |= {_key(e) for e in sheds_admitted}
    lost = [f"{e.get('request')} (run {e.get('run')})" for e in admits
            if _key(e) not in settled_ids]

    lat_all = [e["wall_ms"] for e in results
               if isinstance(e.get("wall_ms"), (int, float))]
    per_bucket: Dict[str, List[float]] = {}
    for e in results:
        if isinstance(e.get("wall_ms"), (int, float)):
            per_bucket.setdefault(str(e.get("bucket")), []).append(
                e["wall_ms"])
    shed_reasons: Dict[str, int] = {}
    for e in sheds:
        r = str(e.get("reason", "unknown"))
        shed_reasons[r] = shed_reasons.get(r, 0) + 1
    deadline_where: Dict[str, int] = {}
    for e in [e for e in events if e.get("event") == "serve_deadline"]:
        w = str(e.get("where", "unknown"))
        deadline_where[w] = deadline_where.get(w, 0) + 1

    batches = [e for e in events if e.get("event") == "serve_batch"]
    # the queue-depth trajectory, downsampled to <= 64 points so a long
    # run's report stays readable
    traj = [{"t": e.get("t"), "queue_depth": e.get("queue_depth"),
             "size": e.get("size")} for e in batches]
    if len(traj) > 64:
        step = len(traj) / 64.0
        traj = [traj[int(i * step)] for i in range(64)]

    # per-replica accounting (the pool postmortem): batches/results/retries
    # per replica id, its latency percentiles, and its death/resurrection
    # count from the replica-tagged serve_health events
    replicas: Dict[str, Dict[str, Any]] = {}

    def _rep(rid) -> Dict[str, Any]:
        return replicas.setdefault(str(rid), {
            "batches": 0, "batch_walls": [], "results": 0, "latencies": [],
            "retries": 0, "deaths": 0, "resurrections": 0, "probes": 0,
        })

    for e in batches:
        if e.get("replica") is not None:
            r = _rep(e["replica"])
            r["batches"] += 1
            if isinstance(e.get("wall_s"), (int, float)):
                r["batch_walls"].append(e["wall_s"])
    for e in results:
        if e.get("replica") is not None:
            r = _rep(e["replica"])
            r["results"] += 1
            if isinstance(e.get("wall_ms"), (int, float)):
                r["latencies"].append(e["wall_ms"])
    for e in events:
        ev, rid = e.get("event"), e.get("replica")
        if rid is None:
            continue
        if ev == "retry" and e.get("scope") == "serving":
            _rep(rid)["retries"] += 1
        elif ev == "serve_health" and e.get("state") == "DEAD":
            _rep(rid)["deaths"] += 1
        elif ev == "serve_health" and e.get("state") == "READY":
            _rep(rid)["resurrections"] += 1
        elif ev == "serve_replica_probe":
            _rep(rid)["probes"] += 1
    replica_table = {}
    for rid, r in sorted(replicas.items()):
        replica_table[rid] = {
            "batches": r["batches"],
            "batch_wall_s": _percentiles(r["batch_walls"]),
            "results": r["results"],
            "latency_ms": _percentiles(r["latencies"]),
            "retries": r["retries"],
            "deaths": r["deaths"],
            "resurrections": r["resurrections"],
            "probes": r["probes"],
        }

    # per-model-version result counts (the PR 18 rollout stamps every
    # serve_result with the serving replica's model_version): a pod that
    # served under more than one version mid-log renders as mixed-version
    by_version: Dict[str, int] = {}
    for e in results:
        if e.get("model_version") is not None:
            v = str(e["model_version"])
            by_version[v] = by_version.get(v, 0) + 1

    return {
        "outcomes": {
            "admitted": len(admits),
            "results": len(results),
            "deadline_exceeded": len(deadlines),
            "quarantined": len(quarantines),
            "shed_admitted": len(sheds_admitted),
            "shed_at_admission": len(sheds) - len(sheds_admitted),
            "terminals": terminals,
            # clamped: a crash in the admit-emit window can lose an admit
            # record for a settled request, and a negative count must not
            # render as "-1 requests died"
            "unresolved": max(0, len(admits) - terminals),
        },
        "results_by_version": by_version,
        "lost_requests": lost,
        "latency_ms": _percentiles(lat_all),
        "latency_ms_by_bucket": {
            b: _percentiles(v) for b, v in sorted(per_bucket.items())},
        "batches": {
            "n": len(batches),
            "wall_s": _percentiles(
                [e["wall_s"] for e in batches
                 if isinstance(e.get("wall_s"), (int, float))]),
            "mean_size": (sum(e.get("size", 0) for e in batches)
                          / len(batches)) if batches else None,
        },
        "queue_depth_trajectory": traj,
        "shed_reasons": shed_reasons,
        "deadline_where": deadline_where,
        "replicas": replica_table,
        # the unified health document the service recorded as its last act
        # (serving/health.py::build_health_document) — the postmortem's
        # final state-of-the-world, schema-versioned
        "final_health_doc": next(
            (e.get("doc") for e in reversed(events)
             if e.get("event") == "serve_health_doc"
             and isinstance(e.get("doc"), dict)), None),
        "health_timeline": [
            {"t": e.get("t"), "state": e.get("state"),
             "reason": e.get("reason"),
             **({"replica": e["replica"]}
                if e.get("replica") is not None else {})}
            for e in events if e.get("event") == "serve_health"
        ],
        "drains": [
            {k: e.get(k) for k in e
             if k.startswith("n_") or k in ("t", "drained", "leftover")}
            for e in events if e.get("event") == "serve_drain"
        ],
    }


def build_rollout_section(events: List[dict]) -> Dict[str, Any]:
    """The rollout postmortem (ncnet_tpu/serving/rollout.py): the phase
    timeline (STAGING -> CANARY -> PROMOTING -> COMPLETE, or the rollback
    branch), every per-replica weight swap with its warmup verdict, the
    canary judgement (PSI per signal, error rate, latency EWMA for old vs
    new), refusals with their classified reasons, and per-model-version
    request accounting recomputed from the version-tagged ``serve_result``/
    ``serve_failure`` stream — the replayable proof that a rollout (or its
    automatic rollback) lost nothing."""
    phases = [
        {k: e.get(k) for k in ("t", "phase", "reason", "old_version",
                               "new_version") if k in e}
        for e in events if e.get("event") == "rollout_phase"
    ]
    swaps = [
        {k: e.get(k) for k in ("t", "replica", "version", "warmed", "ok",
                               "error") if k in e}
        for e in events if e.get("event") == "rollout_swap"
    ]
    refusals = [
        {k: e.get(k) for k in ("t", "candidate", "reason", "error")
         if k in e}
        for e in events if e.get("event") == "rollout_refused"
    ]
    verdicts = [
        {k: e.get(k) for k in
         ("t", "old_version", "new_version", "breach", "psi",
          "psi_threshold", "error_rate", "latency_ewma_ms", "results")
         if k in e}
        for e in events if e.get("event") == "rollout_canary_verdict"
    ]
    rollbacks = [
        {k: e.get(k) for k in ("t", "reason", "old_version", "new_version",
                               "stuck_replicas") if k in e}
        for e in events if e.get("event") == "rollout_rolled_back"
    ]

    # per-version request accounting from the version-tagged result stream:
    # every serve_result/serve_failure carries the model_version of the
    # replica that served it, so the canary's share and the mixed-version
    # window are auditable after the fact
    versions: Dict[str, Dict[str, Any]] = {}

    def _ver(v) -> Dict[str, Any]:
        return versions.setdefault(str(v), {
            "results": 0, "failures": 0, "latencies": [],
        })

    for e in events:
        ev = e.get("event")
        if ev == "serve_result" and e.get("model_version") is not None:
            v = _ver(e["model_version"])
            v["results"] += 1
            if isinstance(e.get("wall_ms"), (int, float)):
                v["latencies"].append(e["wall_ms"])
        elif ev == "serve_failure" and e.get("model_version") is not None:
            _ver(e["model_version"])["failures"] += 1
    version_table = {}
    for vid, v in sorted(versions.items()):
        version_table[vid] = {
            "results": v["results"],
            "failures": v["failures"],
            "latency_ms": _percentiles(v["latencies"]),
        }

    # the DRAINING edges in the health timeline are the capacity evidence:
    # rolling swaps drain exactly one replica at a time
    draining = [
        {"t": e.get("t"), "replica": e.get("replica"),
         "reason": e.get("reason")}
        for e in events
        if e.get("event") == "serve_health"
        and e.get("state") == "DRAINING" and e.get("replica") is not None
    ]

    terminal = None
    for p in phases:
        if p.get("phase") in ("COMPLETE", "ROLLED_BACK", "IDLE"):
            terminal = p["phase"]
    return {
        "phases": phases,
        "terminal_phase": terminal,
        "swaps": swaps,
        "swaps_failed": sum(1 for s in swaps if not s.get("ok")),
        "refusals": refusals,
        "canary_verdicts": verdicts,
        "rollbacks": rollbacks,
        "versions": version_table,
        "replica_drains": draining,
    }


def build_memory_section(events: List[dict]) -> Dict[str, Any]:
    """The memory postmortem (observability/memory.py), replayed from the
    event log alone: the compiled-program ledger table (latest row per
    (program, shape_class, tier, device_kind) key), the HBM trajectory from
    the ``device_snapshot`` events, the leak sentinel's verdicts, and every
    OOM postmortem with its bundled evidence."""
    ledger: Dict[tuple, dict] = {}
    cached = 0
    for e in events:
        if e.get("event") != "memory_ledger":
            continue
        key = (e.get("program"), e.get("shape_class"), e.get("tier"),
               e.get("device_kind"))
        if e.get("source") == "cache":
            cached += 1
        ledger[key] = {
            "program": e.get("program"), "shape_class": e.get("shape_class"),
            "tier": e.get("tier"), "device_kind": e.get("device_kind"),
            "argument_bytes": e.get("argument_bytes"),
            "output_bytes": e.get("output_bytes"),
            "temp_bytes": e.get("temp_bytes"),
            "generated_code_bytes": e.get("generated_code_bytes"),
            "total_bytes": e.get("total_bytes"),
            "source": e.get("source"),
        }

    # HBM trajectory: one point per device_snapshot entry that carried
    # memory stats, downsampled like the queue-depth trajectory
    traj: List[Dict[str, Any]] = []
    for e in events:
        if e.get("event") != "device_snapshot":
            continue
        for d in e.get("devices") or []:
            if isinstance(d, dict) and d.get("bytes_in_use") is not None:
                traj.append({
                    "t": e.get("t"), "device": d.get("id"),
                    "bytes_in_use": d.get("bytes_in_use"),
                    "peak_bytes_in_use": d.get("peak_bytes_in_use"),
                    "bytes_limit": d.get("bytes_limit"),
                    "bytes_reserved": d.get("bytes_reserved"),
                    "largest_free_block_bytes":
                        d.get("largest_free_block_bytes"),
                })
    if len(traj) > 64:
        step = len(traj) / 64.0
        traj = [traj[int(i * step)] for i in range(64)]

    leaks = [
        {k: e.get(k) for k in ("t", "scope", "step", "window", "suspects",
                               "live_n", "live_bytes") if k in e}
        for e in events if e.get("event") == "memory_leak_suspect"
    ]
    postmortems = [
        {k: e.get(k) for k in
         ("t", "scope", "program", "kind", "error", "replica", "phase",
          "bucket", "snapshot", "ledger", "census") if k in e}
        for e in events if e.get("event") == "memory_postmortem"
    ]
    return {
        "ledger": sorted(
            ledger.values(),
            key=lambda r: (str(r["program"]), str(r["shape_class"]),
                           str(r["tier"]))),
        "ledger_events": sum(
            1 for e in events if e.get("event") == "memory_ledger"),
        "ledger_cached_events": cached,
        "hbm_trajectory": traj,
        "leak_suspects": leaks,
        "postmortems": postmortems,
    }


def build_store_section(events: List[dict]) -> Dict[str, Any]:
    """The feature-store replay (ncnet_tpu/store/): per-scope open/stats
    records, the DEGRADED → recovered health timeline, every quarantined
    (corrupt) entry, evictions, and GC sweeps — reconstructed from the
    event log alone, so a dead run's cache behaviour is auditable without
    the store directory."""
    opens = [
        {k: e.get(k) for k in ("t", "scope", "root", "fingerprint",
                               "entries", "bytes", "budget_bytes", "state")
         if k in e}
        for e in events if e.get("event") == "store_open"
    ]
    timeline = [
        {k: e.get(k) for k in ("t", "scope", "state", "reason") if k in e}
        for e in events if e.get("event") == "store_health"
    ]
    corrupt = [
        {k: e.get(k) for k in ("t", "scope", "digest", "reason",
                               "quarantined_to") if k in e}
        for e in events if e.get("event") == "store_corrupt"
    ]
    evictions = [e for e in events if e.get("event") == "store_evict"]
    gcs = [
        {k: e.get(k) for k in ("t", "scope", "fingerprints", "entries")
         if k in e}
        for e in events if e.get("event") == "store_gc"
    ]
    # the last stats flush per scope is the run's final counter state
    stats: Dict[str, Any] = {}
    for e in events:
        if e.get("event") == "store_stats" and isinstance(
                e.get("store"), dict):
            stats[str(e.get("scope", "store"))] = e["store"]
    return {
        "opens": opens,
        "health_timeline": timeline,
        "degraded_spells": sum(
            1 for e in timeline if e.get("state") == "DEGRADED"),
        "recovered": sum(
            1 for e in timeline if e.get("state") == "OK"),
        "corrupt_quarantined": corrupt,
        "evictions": len(evictions),
        "evicted_bytes": sum(
            e.get("bytes", 0) for e in evictions
            if isinstance(e.get("bytes"), (int, float))),
        "gc_sweeps": gcs,
        "final_stats": stats,
    }


def build_router_section(events: List[dict]) -> Dict[str, Any]:
    """The router-tier postmortem (the PR 12 multi-host twin of
    :func:`build_serving_section`): the outcome-total identity recomputed
    at the ROUTER level (``route_admit == route_result + admitted
    route_deadline + route_quarantine + admitted route_shed``; nonzero
    ``unresolved`` = edge requests that died without an outcome),
    backend-tagged accounting (requests/results/retries/backpressure/
    latency per backend id, deaths and resurrections from the
    ``route_backend`` lifecycle events), shed/deadline classification, and
    the router health timeline."""
    admits = [e for e in events if e.get("event") == "route_admit"]
    results = [e for e in events if e.get("event") == "route_result"]
    deadlines = [e for e in events if e.get("event") == "route_deadline"
                 and e.get("admitted") is not False]
    quarantines = [e for e in events
                   if e.get("event") == "route_quarantine"]
    sheds = [e for e in events if e.get("event") == "route_shed"]
    sheds_admitted = [e for e in sheds if e.get("admitted") is True]
    terminals = (len(results) + len(deadlines) + len(quarantines)
                 + len(sheds_admitted))

    def _key(e: dict):
        # keyed (run, request) like the serving section: router request
        # ids restart at q1 per process and a restarted router appends to
        # the same log
        return (e.get("run"), e.get("request"))

    settled = {_key(e) for e in results + quarantines}
    settled |= {_key(e) for e in deadlines}
    settled |= {_key(e) for e in sheds_admitted}
    lost = [f"{e.get('request')} (run {e.get('run')})" for e in admits
            if _key(e) not in settled]

    lat_all = [e["wall_ms"] for e in results
               if isinstance(e.get("wall_ms"), (int, float))]
    shed_reasons: Dict[str, int] = {}
    for e in sheds:
        r = str(e.get("reason", "unknown"))
        shed_reasons[r] = shed_reasons.get(r, 0) + 1
    deadline_where: Dict[str, int] = {}
    for e in [e for e in events if e.get("event") == "route_deadline"]:
        w = str(e.get("where", "unknown"))
        deadline_where[w] = deadline_where.get(w, 0) + 1

    # backend-tagged accounting: results/latency per backend, retries and
    # backpressure from the scope="router" retry events, lifecycle from
    # route_backend, probes from route_backend_probe
    backends: Dict[str, Dict[str, Any]] = {}

    def _bk(bid) -> Dict[str, Any]:
        return backends.setdefault(str(bid), {
            "results": 0, "latencies": [], "backend_wall_ms": [],
            "retries": 0, "backpressure": 0, "deaths": 0,
            "resurrections": 0, "draining": 0, "probes": 0,
        })

    for e in results:
        if e.get("backend") is not None:
            b = _bk(e["backend"])
            b["results"] += 1
            if isinstance(e.get("wall_ms"), (int, float)):
                b["latencies"].append(e["wall_ms"])
            if isinstance(e.get("backend_wall_ms"), (int, float)):
                b["backend_wall_ms"].append(e["backend_wall_ms"])
    for e in events:
        ev, bid = e.get("event"), e.get("backend")
        if bid is None:
            continue
        if ev == "retry" and e.get("scope") == "router":
            b = _bk(bid)
            if e.get("via") == "backpressure":
                b["backpressure"] += 1
            else:
                b["retries"] += 1
        elif ev == "route_backend" and e.get("state") == "DEAD":
            _bk(bid)["deaths"] += 1
        elif ev == "route_backend" and e.get("state") == "READY":
            _bk(bid)["resurrections"] += 1
        elif ev == "route_backend" and e.get("state") == "DRAINING":
            _bk(bid)["draining"] += 1
        elif ev == "route_backend_probe":
            _bk(bid)["probes"] += 1
    backend_table = {}
    for bid, b in sorted(backends.items()):
        backend_table[bid] = {
            "results": b["results"],
            "latency_ms": _percentiles(b["latencies"]),
            # the fan-out overhead evidence: edge wall minus the wall the
            # backend itself reported for the same requests
            "backend_wall_ms": _percentiles(b["backend_wall_ms"]),
            "retries": b["retries"],
            "backpressure": b["backpressure"],
            "deaths": b["deaths"],
            "resurrections": b["resurrections"],
            "draining": b["draining"],
            "probes": b["probes"],
        }

    return {
        "outcomes": {
            "admitted": len(admits),
            "results": len(results),
            "deadline_exceeded": len(deadlines),
            "quarantined": len(quarantines),
            "shed_admitted": len(sheds_admitted),
            "shed_at_admission": len(sheds) - len(sheds_admitted),
            "terminals": terminals,
            "unresolved": max(0, len(admits) - terminals),
        },
        "lost_requests": lost,
        "latency_ms": _percentiles(lat_all),
        "shed_reasons": shed_reasons,
        "deadline_where": deadline_where,
        "backends": backend_table,
        "final_health_doc": next(
            (e.get("doc") for e in reversed(events)
             if e.get("event") == "route_health_doc"
             and isinstance(e.get("doc"), dict)), None),
        "health_timeline": [
            {"t": e.get("t"), "state": e.get("state"),
             "reason": e.get("reason"),
             **({"backend": e["backend"]}
                if e.get("backend") is not None else {})}
            for e in events
            if e.get("event") in ("route_health", "route_backend")
        ],
        "drains": [
            {k: e.get(k) for k in e
             if k.startswith("n_") or k in ("t", "drained", "leftover")}
            for e in events if e.get("event") == "route_drain"
        ],
    }


def build_pod_section(events: List[dict]) -> Dict[str, Any]:
    """The POD-scope identity report (``--pod log1 log2 ...``): the
    outcome-total invariant recomputed across EVERY log of a pod at once,
    joined by the wire-propagated trace id (observability/tracing.py).

    What only the merged logs can prove:

      * **edge totality** — every router-admitted request reaches exactly
        one terminal ``route_*`` outcome (same identity as the router
        section, but over all router logs/lineages in the pod);
      * **trail continuity** — every ``route_result`` was BACKED by a
        ``serve_result`` carrying the same trace id in some backend log.
        A trace whose router says "result" but whose backend trail shows
        fewer results has GONE DARK (a backend log lost/torn past its
        settle) and is named, never averaged away;
      * **failover attribution** — each ``retry`` ``scope=router``
        ``via=reroute`` is tied to its trace, with the backend runs that
        admitted the request before and after, so a SIGKILLed backend's
        re-routed requests are individually accounted;
      * **hedge attribution** — ``retrieve_hedge`` events joined by
        trace, the shard tier's duplicate-dispatch accounting;
      * **pod overhead** — per routed result, the edge wall minus the
        wall the backend measured for the SAME trace = wire + routing
        overhead (falls back to the in-band ``backend_wall_ms`` when the
        trace join finds no unique backend twin, e.g. shared stream
        traces).
    """
    def _key(e: dict):
        return (e.get("run"), e.get("request"))

    admits = [e for e in events if e.get("event") == "route_admit"]
    r_results = [e for e in events if e.get("event") == "route_result"]
    r_deadlines = [e for e in events if e.get("event") == "route_deadline"
                   and e.get("admitted") is not False]
    r_quar = [e for e in events if e.get("event") == "route_quarantine"]
    r_sheds = [e for e in events if e.get("event") == "route_shed"
               and e.get("admitted") is True]
    terminals = (len(r_results) + len(r_deadlines) + len(r_quar)
                 + len(r_sheds))
    settled = {_key(e) for e in r_results + r_quar}
    settled |= {_key(e) for e in r_deadlines}
    settled |= {_key(e) for e in r_sheds}
    lost = [f"{e.get('request')} (run {e.get('run')})" for e in admits
            if _key(e) not in settled]

    # --- the trace join across logs -----------------------------------
    s_results = [e for e in events if e.get("event") == "serve_result"
                 and e.get("trace")]
    s_admit_runs: Dict[str, List[Any]] = {}
    for e in events:
        if e.get("event") == "serve_admit" and e.get("trace"):
            runs = s_admit_runs.setdefault(str(e["trace"]), [])
            if e.get("run") not in runs:
                runs.append(e.get("run"))
    serve_by_trace: Dict[str, List[dict]] = {}
    for e in s_results:
        serve_by_trace.setdefault(str(e["trace"]), []).append(e)
    route_by_trace: Dict[str, List[dict]] = {}
    for e in r_results:
        if e.get("trace"):
            route_by_trace.setdefault(str(e["trace"]), []).append(e)

    # trail continuity: a trace the router settled as result must show at
    # least as many backend results across the pod's logs
    dark: List[Dict[str, Any]] = []
    for tr, routed in sorted(route_by_trace.items()):
        served = serve_by_trace.get(tr, [])
        if len(served) < len(routed):
            dark.append({
                "trace": tr,
                "router_requests": sorted(
                    str(e.get("request")) for e in routed),
                "route_results": len(routed),
                "backend_results": len(served),
                "backend_runs": s_admit_runs.get(tr, []),
            })
    # admitted at a backend under a router trace but never settled there:
    # the in-flight-at-SIGKILL population, attributed by trace
    s_settled = {_key(e) for e in events
                 if e.get("event") in ("serve_result", "serve_quarantine")
                 or (e.get("event") == "serve_deadline"
                     and e.get("admitted") is not False)
                 or (e.get("event") == "serve_shed"
                     and e.get("admitted") is True)}
    backend_lost = [
        {"trace": str(e.get("trace")), "request": str(e.get("request")),
         "run": e.get("run")}
        for e in events
        if e.get("event") == "serve_admit" and e.get("trace")
        and _key(e) not in s_settled]

    # failover attribution: every router reroute tied to its trace and
    # the backend runs that saw the request before/after
    failovers = []
    for e in events:
        if e.get("event") == "retry" and e.get("scope") == "router" \
                and e.get("via") == "reroute":
            tr = str(e.get("trace")) if e.get("trace") else None
            failovers.append({
                "request": e.get("unit"), "trace": tr,
                "kind": e.get("kind"), "from_backend": e.get("backend"),
                "backend_runs": (s_admit_runs.get(tr, [])
                                 if tr else []),
                "recovered": bool(tr and route_by_trace.get(tr)),
            })
    hedges = [
        {"request": e.get("request"), "trace": e.get("trace"),
         "shard": e.get("shard"), "panos": e.get("panos")}
        for e in events
        if e.get("event") == "retrieve_hedge" and e.get("trace")]

    # pod overhead: edge wall minus the backend's own wall per request —
    # via the trace join when it is unique, in-band backend_wall_ms else
    overhead: List[float] = []
    joined = 0
    for e in r_results:
        if not isinstance(e.get("wall_ms"), (int, float)):
            continue
        tr = str(e.get("trace")) if e.get("trace") else None
        twins = serve_by_trace.get(tr, []) if tr else []
        if tr and len(twins) == 1 and len(route_by_trace.get(tr, [])) == 1 \
                and isinstance(twins[0].get("wall_ms"), (int, float)):
            overhead.append(float(e["wall_ms"])
                            - float(twins[0]["wall_ms"]))
            joined += 1
        elif isinstance(e.get("backend_wall_ms"), (int, float)):
            overhead.append(float(e["wall_ms"])
                            - float(e["backend_wall_ms"]))

    traced_admits = sum(1 for e in admits if e.get("trace"))
    return {
        "outcomes": {
            "admitted": len(admits),
            "results": len(r_results),
            "deadline_exceeded": len(r_deadlines),
            "quarantined": len(r_quar),
            "shed_admitted": len(r_sheds),
            "terminals": terminals,
            "unresolved": max(0, len(admits) - terminals),
        },
        "lost_requests": lost,
        "traced_admits": traced_admits,
        "traces": {
            "routed": len(route_by_trace),
            "backed": sum(1 for tr in route_by_trace
                          if tr in serve_by_trace),
        },
        "dark_trails": dark,
        "backend_lost": backend_lost,
        "failovers": failovers,
        "hedges": hedges,
        "overhead_ms": _percentiles(overhead),
        "overhead_joined_by_trace": joined,
        "overhead_samples": len(overhead),
    }


def build_retrieval_section(events: List[dict]) -> Dict[str, Any]:
    """The retrieval-tier postmortem (ncnet_tpu/retrieval/): the
    outcome-total identity at the COORDINATOR level (``retrieve_admit ==
    retrieve_result + retrieve_deadline + retrieve_shed``; results split
    into full-coverage and degraded), the coverage distribution with its
    never-silent floor, the hedge rate, per-shard outcome accounting, and
    the shard death/resurrection timeline — all replayed from the log."""
    admits = [e for e in events if e.get("event") == "retrieve_admit"]
    results = [e for e in events if e.get("event") == "retrieve_result"]
    deadlines = [e for e in events
                 if e.get("event") == "retrieve_deadline"]
    sheds = [e for e in events if e.get("event") == "retrieve_shed"]
    hedges = [e for e in events if e.get("event") == "retrieve_hedge"]
    degraded = [e for e in results if e.get("degraded")]
    terminals = len(results) + len(deadlines) + len(sheds)

    def _key(e: dict):
        return (e.get("run"), e.get("request"))

    settled = {_key(e) for e in results + deadlines + sheds}
    lost = [f"{e.get('request')} (run {e.get('run')})" for e in admits
            if _key(e) not in settled]

    covs = [e["coverage"] for e in results
            if isinstance(e.get("coverage"), (int, float))]
    walls = [e["wall_ms"] for e in results
             if isinstance(e.get("wall_ms"), (int, float))]
    hedged_queries = sum(
        1 for e in results if (e.get("hedges") or 0) > 0)

    # per-shard accounting: results/walls from retrieve_shard_result,
    # error kinds from retrieve_shard_error, lifecycle from
    # retrieve_backend, hedges targeted at the shard from retrieve_hedge
    shards: Dict[str, Dict[str, Any]] = {}

    def _sh(sid) -> Dict[str, Any]:
        return shards.setdefault(str(sid), {
            "results": 0, "walls": [], "consulted": 0, "unavailable": 0,
            "errors": {}, "deaths": 0, "resurrections": 0, "draining": 0,
            "hedges": 0,
        })

    for e in events:
        ev, sid = e.get("event"), e.get("shard")
        if sid is None:
            continue
        if ev == "retrieve_shard_result":
            s = _sh(sid)
            s["results"] += 1
            s["consulted"] += e.get("consulted") or 0
            s["unavailable"] += e.get("unavailable") or 0
            if isinstance(e.get("wall_ms"), (int, float)):
                s["walls"].append(e["wall_ms"])
        elif ev == "retrieve_shard_error":
            k = str(e.get("kind", "other"))
            s = _sh(sid)
            s["errors"][k] = s["errors"].get(k, 0) + 1
        elif ev == "retrieve_backend":
            st = e.get("state")
            if st == "DEAD":
                _sh(sid)["deaths"] += 1
            elif st == "READY":
                _sh(sid)["resurrections"] += 1
            elif st == "DRAINING":
                _sh(sid)["draining"] += 1
        elif ev == "retrieve_hedge":
            _sh(sid)["hedges"] += 1
    shard_table = {}
    for sid, s in sorted(shards.items()):
        shard_table[sid] = {
            "results": s["results"],
            "wall_ms": _percentiles(s["walls"]),
            "consulted": s["consulted"],
            "unavailable": s["unavailable"],
            "errors": s["errors"],
            "deaths": s["deaths"],
            "resurrections": s["resurrections"],
            "draining": s["draining"],
            "hedges_absorbed": s["hedges"],
        }

    out: Dict[str, Any] = {
        "outcomes": {
            "admitted": len(admits),
            "results": len(results),
            "results_degraded": len(degraded),
            "deadline_exceeded": len(deadlines),
            "shed": len(sheds),
            "terminals": terminals,
            "unresolved": max(0, len(admits) - terminals),
        },
        "lost_requests": lost,
        "coverage": {
            **_percentiles(covs),
            "min": round(min(covs), 6) if covs else None,
            "below_full": sum(1 for c in covs if c < 1.0),
        },
        "latency_ms": _percentiles(walls),
        "hedging": {
            "hedge_dispatches": len(hedges),
            "hedged_queries": hedged_queries,
            "hedge_rate_pct": round(
                100.0 * hedged_queries / max(1, len(results)), 2),
        },
        "shards": shard_table,
        "timeline": [
            {"t": e.get("t"), "state": e.get("state"),
             "reason": e.get("reason"),
             **({"shard": e["shard"]} if e.get("shard") is not None
                else {})}
            for e in events
            if e.get("event") in ("retrieve_health", "retrieve_backend")
        ],
        "final_health_doc": next(
            (e.get("doc") for e in reversed(events)
             if e.get("event") == "retrieve_health_doc"
             and isinstance(e.get("doc"), dict)), None),
    }
    # the InLoc in-system shortlist's events ride the same section: how
    # often retrieval actually reordered a query vs fell back, and why
    shortlists = [e for e in events
                  if e.get("event") == "retrieval_shortlist"]
    fallbacks = [e for e in events
                 if e.get("event") == "retrieval_fallback"]
    if shortlists or fallbacks:
        reasons: Dict[str, int] = {}
        for e in fallbacks:
            r = str(e.get("reason", "unknown"))
            reasons[r] = reasons.get(r, 0) + 1
        out["inloc_shortlist"] = {
            "reordered": len(shortlists),
            "fallbacks": len(fallbacks),
            "fallback_reasons": reasons,
            "coverage": _percentiles(
                [e["coverage"] for e in shortlists
                 if isinstance(e.get("coverage"), (int, float))]),
        }
    return out


def build_report(paths: List[str],
                 quality_ref: Optional[str] = None) -> Dict[str, Any]:
    """Aggregate one report dict over every given event log."""
    runs: List[Dict[str, Any]] = []
    events: List[dict] = []
    for path in paths:
        header, recs = replay_events(path)
        runs.append({"path": path, "header": header.get("header", {})})
        events.extend(recs)

    steps = [e for e in events if e.get("event") == "step"]
    step_walls = [e["wall_s"] for e in steps if isinstance(
        e.get("wall_s"), (int, float))]
    stage_walls = [e["stage_wall_s"] for e in steps if isinstance(
        e.get("stage_wall_s"), (int, float))]
    mfu = [(e.get("step"), e["mfu_pct"]) for e in steps
           if isinstance(e.get("mfu_pct"), (int, float))]
    pairs_s = [e["pairs_per_s"] for e in steps
               if isinstance(e.get("pairs_per_s"), (int, float))]

    # run/resume lineage: order of first appearance of each run id
    lineage: List[Dict[str, Any]] = []
    seen_runs: Dict[str, int] = {}
    for e in events:
        rid = e.get("run")
        if rid and rid not in seen_runs:
            seen_runs[rid] = len(lineage)
            lineage.append({"run_id": rid, "events": 0})
        if rid:
            lineage[seen_runs[rid]]["events"] += 1
        if e.get("event") == "resume" and rid:
            lineage[seen_runs[rid]]["resumed_from"] = {
                "checkpoint": e.get("checkpoint"),
                "epoch": e.get("epoch"), "batch": e.get("batch"),
                "step": e.get("step"),
            }

    tier_timeline = [
        {k: e.get(k) for k in
         ("t", "event", "tier", "stage", "shape", "demoted") if k in e}
        for e in events if e.get("event") in ("tier_selected", "tier_demoted")
    ]

    retries_by_kind: Dict[str, int] = {}
    for e in events:
        if e.get("event") == "retry":
            k = str(e.get("kind", "other"))
            retries_by_kind[k] = retries_by_kind.get(k, 0) + 1
    quarantines = [
        {"unit": e.get("unit"), "kind": e.get("kind"),
         "attempts": e.get("attempts"), "scope": e.get("scope")}
        for e in events if e.get("event") == "quarantine"
    ]

    checkpoints = [
        {"step": e.get("step"), "epoch": e.get("epoch"),
         "best": e.get("best"), "path": e.get("path")}
        for e in events if e.get("event") == "checkpoint_commit"
    ]
    nan_skips = [e for e in events if e.get("event") == "nan_skip"]
    diverged = [e for e in events if e.get("event") == "diverged"]
    preemptions = [e for e in events if e.get("event") == "preemption"]
    watchdogs = [e for e in events if e.get("event") == "watchdog_timeout"]
    run_ends = [e for e in events if e.get("event") == "run_end"]

    postmortem: Optional[Dict[str, Any]] = None
    if diverged:
        death = diverged[-1]
        tail = [e for e in steps
                if isinstance(e.get("step"), int)
                and e["step"] <= (death.get("step") or 0)][-8:]
        postmortem = {
            "died_at_step": death.get("step"),
            "epoch": death.get("epoch"),
            "streak": death.get("streak"),
            "last_steps": [
                {k: e.get(k) for k in
                 ("step", "loss", "grad_norm", "wall_s") if k in e}
                for e in tail
            ],
        }

    eval_batches = [e for e in events if e.get("event") == "eval_batch"]
    eval_queries = [e for e in events if e.get("event") == "eval_query"]
    eval_summaries = [e for e in events
                      if e.get("event") == "eval_summary"]

    report: Dict[str, Any] = {
        "logs": runs,
        "lineage": lineage,
        "counts": {
            "events": len(events),
            "steps": len(steps),
            "epochs_completed": sum(
                1 for e in events if e.get("event") == "epoch_end"),
            "checkpoint_commits": len(checkpoints),
            "resumes": sum(
                1 for e in events if e.get("event") == "resume"),
            "nan_skips": len(nan_skips),
            "preemptions": len(preemptions),
            "watchdog_timeouts": len(watchdogs),
            "quarantines": len(quarantines),
            "tier_demotions": sum(
                1 for e in events if e.get("event") == "tier_demoted"),
            "run_ends": len(run_ends),
        },
        "step_wall_s": _percentiles(step_walls),
        "stage_wall_s": _percentiles(stage_walls),
        "pairs_per_s": _percentiles(pairs_s),
        "mfu_trajectory": [{"step": s, "mfu_pct": m} for s, m in mfu],
        "tier_timeline": tier_timeline,
        "retries_by_kind": retries_by_kind,
        "quarantines": quarantines,
        "checkpoints": checkpoints,
        "divergence_postmortem": postmortem,
    }
    if any(e.get("event") == "span" for e in events):
        report["spans"] = build_span_breakdown(events)
    if any(str(e.get("event", "")).startswith("serve_") for e in events):
        report["serving"] = build_serving_section(events)
        report["slo"] = build_slo_section(events)
    if any(str(e.get("event", "")).startswith("rollout_") for e in events):
        report["rollout"] = build_rollout_section(events)
    if any(str(e.get("event", "")).startswith("route_") for e in events):
        report["router"] = build_router_section(events)
        report["pod"] = build_pod_section(events)
    if any(str(e.get("event", "")).startswith(("retrieve_", "retrieval_"))
           for e in events):
        report["retrieval"] = build_retrieval_section(events)
    if any(e.get("event") in ("memory_ledger", "memory_leak_suspect",
                              "memory_postmortem", "device_snapshot")
           for e in events):
        report["memory"] = build_memory_section(events)
    if any(str(e.get("event", "")).startswith("store_") for e in events):
        report["store"] = build_store_section(events)
    if any(e.get("event") == "quality" for e in events):
        device_kind = next(
            (r["header"].get("device_kind") for r in runs
             if r["header"].get("device_kind")), None)
        report["quality"] = build_quality_section(
            events, device_kind, ref_path=quality_ref)
    if eval_batches or eval_queries or eval_summaries:
        pcks = [e["pck"] for e in eval_batches
                if isinstance(e.get("pck"), (int, float))]
        report["eval"] = {
            "batches": len(eval_batches),
            "queries": len(eval_queries),
            "queries_ok": sum(1 for e in eval_queries if e.get("ok")),
            "batch_pck": _percentiles(pcks, qs=(50,)),
            "fetch_wall_s": _percentiles(
                [e["fetch_wall_s"] for e in eval_batches
                 if isinstance(e.get("fetch_wall_s"), (int, float))]),
            "summaries": eval_summaries,
        }
    return report


def _fmt_stats(stats: Dict[str, float], unit: str = "s") -> str:
    if not stats:
        return "(no samples)"
    parts = [f"{k}={stats[k]:.4f}{unit}" for k in ("p50", "p90", "p99")
             if k in stats]
    parts.append(f"mean={stats['mean']:.4f}{unit}")
    parts.append(f"n={stats['n']}")
    return "  ".join(parts)


def render_spans(report: Dict[str, Any]) -> str:
    sp = report.get("spans")
    if not sp or not sp["groups"]:
        return "(no span events in the log)"
    lines = ["span breakdown (self-time ranked; parent > name):"]
    width = max(len(f"{g['parent']} > {g['name']}") for g in sp["groups"])
    for g in sp["groups"]:
        label = f"{g['parent']} > {g['name']}"
        err = f"  errors={g['errors']}" if g["errors"] else ""
        lines.append(
            f"  {label:<{width}}  n={g['n']:<6} self={g['self_s']:<10.4f} "
            f"total={g['total_s']:<10.4f} mean={g['mean_s']:.4f}s{err}")
    if sp["unclosed"]:
        lines.append(f"  ({sp['unclosed']} unclosed span(s) — in flight at "
                     "process death)")
    return "\n".join(lines)


def render_quality(report: Dict[str, Any]) -> str:
    q = report.get("quality")
    if not q or not q["table"]:
        return "(no quality events in the log)"
    lines = ["quality signals (per tier):"]
    # a (tier, signal) whose every sample was NaN (all pairs quarantined
    # under that tier) has count 0 and None stats — render, don't crash
    fmt = lambda v: "n/a" if v is None else v  # noqa: E731
    for row in q["table"]:
        lines.append(
            f"  {row['tier']:<12} {row['signal']:<14} n={row['n']:<6} "
            f"mean={fmt(row['mean']):<8} p50={fmt(row['p50']):<8} "
            f"p90={fmt(row['p90'])}")
    rho = q.get("pck_spearman")
    if rho:
        lines.append("signal-vs-PCK rank correlation (Spearman):")
        for name, v in sorted(rho.items()):
            lines.append(f"  {name:<14} rho={'n/a' if v is None else v}")
    drift = q.get("drift")
    if drift is not None:
        lines.append(f"drift vs {q.get('drift_ref')}:")
        for f in drift:
            if f["status"] == "skipped":
                lines.append(f"  [skipped] {f['tier']}/{f['signal']}  "
                             f"({f['reason']})")
            else:
                tag = "DRIFT" if f["status"] == "drift" else "ok"
                lines.append(
                    f"  [{tag}] {f['tier']}/{f['signal']}  "
                    f"psi={f['psi']:.4f} (threshold {f['threshold']})")
    return "\n".join(lines)


def render_serving(report: Dict[str, Any]) -> str:
    sv = report.get("serving")
    if not sv:
        return "(no serving events in the log)"
    lines = ["serving:"]
    o = sv["outcomes"]
    lines.append(
        f"  outcomes: admitted={o['admitted']}  results={o['results']}  "
        f"deadline={o['deadline_exceeded']}  quarantined={o['quarantined']}  "
        f"shed_admitted={o['shed_admitted']}  "
        f"shed_at_admission={o['shed_at_admission']}")
    if o["unresolved"]:
        lines.append(
            f"  UNRESOLVED: {o['unresolved']} admitted request(s) died "
            f"without an outcome (lost mid-drain/crash): "
            f"{', '.join(str(r) for r in sv['lost_requests'][:16])}")
    else:
        lines.append("  outcome-total: every admitted request reached "
                     "exactly one terminal outcome")
    if sv.get("results_by_version"):
        vs = sv["results_by_version"]
        tag = "MIXED-VERSION pod" if len(vs) > 1 else "single version"
        lines.append("  results by model version (" + tag + "): "
                     + ", ".join(f"{k}={v}" for k, v in sorted(vs.items())))
    if sv["latency_ms"]:
        lines.append(f"  latency:  {_fmt_stats(sv['latency_ms'], 'ms')}")
    for b, stats in sv["latency_ms_by_bucket"].items():
        lines.append(f"    {b}: {_fmt_stats(stats, 'ms')}")
    bt = sv["batches"]
    if bt["n"]:
        lines.append(
            f"  batches: n={bt['n']}  mean_size={bt['mean_size']:.2f}  "
            f"wall {_fmt_stats(bt['wall_s'])}")
    if sv["shed_reasons"]:
        lines.append("  shed by reason: " + ", ".join(
            f"{k}={v}" for k, v in sorted(sv["shed_reasons"].items())))
    if sv["deadline_where"]:
        lines.append("  deadlines by checkpoint: " + ", ".join(
            f"{k}={v}" for k, v in sorted(sv["deadline_where"].items())))
    if sv.get("replicas"):
        lines.append("  replicas:")
        for rid, r in sv["replicas"].items():
            chaos = ""
            if r["deaths"] or r["resurrections"]:
                chaos = (f"  deaths={r['deaths']} "
                         f"resurrections={r['resurrections']} "
                         f"probes={r['probes']}")
            lines.append(
                f"    {rid}: batches={r['batches']}  results={r['results']}"
                f"  retries={r['retries']}{chaos}")
            if r["latency_ms"]:
                lines.append(
                    f"      latency {_fmt_stats(r['latency_ms'], 'ms')}")
    if sv["health_timeline"]:
        lines.append("  health timeline:")
        for h in sv["health_timeline"]:
            who = f"[{h['replica']}] " if h.get("replica") else ""
            lines.append(f"    -> {who}{h['state']}"
                         + (f"  ({h['reason']})" if h.get("reason") else ""))
    if sv["queue_depth_trajectory"]:
        depths = [p["queue_depth"] for p in sv["queue_depth_trajectory"]
                  if isinstance(p.get("queue_depth"), (int, float))]
        if depths:
            lines.append(f"  queue depth: first={depths[0]} "
                         f"max={max(depths)} last={depths[-1]} "
                         f"({len(depths)} samples)")
    for d in sv["drains"]:
        lines.append(f"  drain: drained={d.get('drained')} "
                     f"leftover={d.get('leftover')}")
    fh = sv.get("final_health_doc")
    if fh:
        pool = fh.get("pool", {})
        lines.append(
            f"  final health doc (schema {fh.get('schema')}): "
            f"state={fh.get('state')}  pool "
            f"{pool.get('ready')}/{pool.get('total')} ready  "
            f"counters={fh.get('counters')}")
    return "\n".join(lines)


def render_router(report: Dict[str, Any]) -> str:
    rt = report.get("router")
    if not rt:
        return "(no router events in the log)"
    lines = ["router (multi-host tier):"]
    o = rt["outcomes"]
    lines.append(
        f"  outcomes: admitted={o['admitted']}  results={o['results']}  "
        f"deadline={o['deadline_exceeded']}  quarantined={o['quarantined']}"
        f"  shed_admitted={o['shed_admitted']}  "
        f"shed_at_admission={o['shed_at_admission']}")
    if o["unresolved"]:
        lines.append(
            f"  UNRESOLVED: {o['unresolved']} admitted request(s) died "
            f"without an outcome: "
            f"{', '.join(str(r) for r in rt['lost_requests'][:16])}")
    else:
        lines.append("  outcome-total: every admitted request reached "
                     "exactly one terminal outcome")
    if rt["latency_ms"]:
        lines.append(f"  latency:  {_fmt_stats(rt['latency_ms'], 'ms')}")
    if rt["shed_reasons"]:
        lines.append("  shed by reason: " + ", ".join(
            f"{k}={v}" for k, v in sorted(rt["shed_reasons"].items())))
    if rt["deadline_where"]:
        lines.append("  deadlines by checkpoint: " + ", ".join(
            f"{k}={v}" for k, v in sorted(rt["deadline_where"].items())))
    if rt.get("backends"):
        lines.append("  backends:")
        for bid, b in rt["backends"].items():
            chaos = ""
            if b["deaths"] or b["resurrections"] or b["draining"]:
                chaos = (f"  deaths={b['deaths']} "
                         f"resurrections={b['resurrections']} "
                         f"draining={b['draining']} probes={b['probes']}")
            lines.append(
                f"    {bid}: results={b['results']}  retries={b['retries']}"
                f"  backpressure={b['backpressure']}{chaos}")
            if b["latency_ms"]:
                lines.append(
                    f"      edge latency {_fmt_stats(b['latency_ms'], 'ms')}")
            if b["backend_wall_ms"]:
                lines.append(
                    f"      backend wall "
                    f"{_fmt_stats(b['backend_wall_ms'], 'ms')} "
                    "(edge minus this = fan-out overhead)")
    if rt["health_timeline"]:
        lines.append("  health timeline:")
        for h in rt["health_timeline"]:
            who = f"[{h['backend']}] " if h.get("backend") else ""
            lines.append(f"    -> {who}{h['state']}"
                         + (f"  ({h['reason']})" if h.get("reason") else ""))
    for d in rt["drains"]:
        lines.append(f"  drain: drained={d.get('drained')} "
                     f"leftover={d.get('leftover')}")
    fh = rt.get("final_health_doc")
    if fh:
        pod = fh.get("pod", {})
        lines.append(
            f"  final health doc (schema {fh.get('schema')}): "
            f"state={fh.get('state')}  pod "
            f"{pod.get('ready')}/{pod.get('total')} backends ready "
            f"({pod.get('replicas_ready')}/{pod.get('replicas_total')} "
            f"replica units)  counters={fh.get('counters')}")
    return "\n".join(lines)


def render_pod(report: Dict[str, Any]) -> str:
    pod = report.get("pod")
    if not pod:
        return "(no route_* events in the logs — a pod report needs the " \
               "router's log alongside the backend logs)"
    lines = ["pod (trace-joined across all given logs):"]
    o = pod["outcomes"]
    lines.append(
        f"  edge outcomes: admitted={o['admitted']}  "
        f"results={o['results']}  deadline={o['deadline_exceeded']}  "
        f"quarantined={o['quarantined']}  "
        f"shed_admitted={o['shed_admitted']}")
    if o["unresolved"]:
        lines.append(
            f"  EDGE UNRESOLVED: {o['unresolved']} admitted request(s) "
            f"died without an outcome: "
            f"{', '.join(str(r) for r in pod['lost_requests'][:16])}")
    else:
        lines.append("  edge outcome-total: every router-admitted request "
                     "reached exactly one terminal outcome")
    tr = pod["traces"]
    lines.append(
        f"  traces: {pod['traced_admits']}/{o['admitted']} admits traced"
        f"  routed-result traces={tr['routed']}  "
        f"backed-by-backend={tr['backed']}")
    if pod["dark_trails"]:
        lines.append(f"  DARK TRAILS: {len(pod['dark_trails'])} trace(s) "
                     "the router settled as result without a matching "
                     "backend serve_result in ANY log:")
        for d in pod["dark_trails"][:16]:
            lines.append(
                f"    {d['trace'][:16]}…  router req(s) "
                f"{','.join(d['router_requests'])}  "
                f"route_results={d['route_results']} "
                f"backend_results={d['backend_results']}")
    else:
        lines.append("  trail continuity: every routed result is backed "
                     "by a same-trace backend result")
    if pod["backend_lost"]:
        lines.append(f"  backend in-flight at death: "
                     f"{len(pod['backend_lost'])} traced admit(s) never "
                     "settled on their backend:")
        for b in pod["backend_lost"][:16]:
            lines.append(f"    {b['trace'][:16]}…  {b['request']} "
                         f"(run {b['run']})")
    if pod["failovers"]:
        lines.append(f"  failovers: {len(pod['failovers'])} router "
                     "re-route(s), each attributed to its trace:")
        for f in pod["failovers"][:16]:
            t = (f["trace"][:16] + "…") if f.get("trace") else "(untraced)"
            runs = ",".join(str(r) for r in f.get("backend_runs", []))
            lines.append(
                f"    {f['request']}  {t}  kind={f['kind']} "
                f"from={f['from_backend']}  backend runs [{runs}]  "
                + ("recovered" if f.get("recovered") else "NOT recovered"))
    if pod["hedges"]:
        lines.append(f"  hedged shard dispatches: {len(pod['hedges'])} "
                     "(trace-attributed)")
    if pod["overhead_ms"]:
        lines.append(
            f"  wire+routing overhead (edge wall − backend wall): "
            f"{_fmt_stats(pod['overhead_ms'], 'ms')}  "
            f"[{pod['overhead_joined_by_trace']}/"
            f"{pod['overhead_samples']} joined by trace]")
    return "\n".join(lines)


def _fmt_bytes(v) -> str:
    if not isinstance(v, (int, float)):
        return "-"
    if abs(v) >= 2 ** 20:
        return f"{v / 2 ** 20:.1f}MiB"
    if abs(v) >= 2 ** 10:
        return f"{v / 2 ** 10:.1f}KiB"
    return f"{int(v)}B"


def render_memory(report: Dict[str, Any]) -> str:
    m = report.get("memory")
    if not m:
        return "(no memory events in the log)"
    lines = ["memory (observability/memory.py, replayed from the log):"]
    add = lines.append
    if m["ledger"]:
        add(f"  compiled-program ledger ({len(m['ledger'])} program(s); "
            f"{m['ledger_events']} event(s), "
            f"{m['ledger_cached_events']} cache-replayed):")
        width = max(len(str(r["program"])) for r in m["ledger"])
        for r in m["ledger"]:
            add(f"    {str(r['program']):<{width}}  "
                f"args={_fmt_bytes(r['argument_bytes']):>9} "
                f"out={_fmt_bytes(r['output_bytes']):>9} "
                f"temp={_fmt_bytes(r['temp_bytes']):>9} "
                f"total={_fmt_bytes(r['total_bytes']):>9}  "
                f"tier={r['tier']}  [{r['shape_class']}] "
                f"({r['device_kind']})")
    else:
        add("  compiled-program ledger: (no memory_ledger events)")
    traj = m["hbm_trajectory"]
    if traj:
        in_use = [p["bytes_in_use"] for p in traj
                  if isinstance(p.get("bytes_in_use"), (int, float))]
        peaks = [p["peak_bytes_in_use"] for p in traj
                 if isinstance(p.get("peak_bytes_in_use"), (int, float))]
        limit = next((p["bytes_limit"] for p in traj
                      if p.get("bytes_limit")), None)
        add(f"  HBM trajectory ({len(traj)} snapshot(s)): "
            f"first={_fmt_bytes(in_use[0]) if in_use else '-'} "
            f"max={_fmt_bytes(max(in_use)) if in_use else '-'} "
            f"last={_fmt_bytes(in_use[-1]) if in_use else '-'} "
            f"peak={_fmt_bytes(max(peaks)) if peaks else '-'}"
            + (f" limit={_fmt_bytes(limit)}" if limit else ""))
    else:
        add("  HBM trajectory: (no device_snapshot memory stats — CPU "
            "backend, or the monitor never fired)")
    if m["leak_suspects"]:
        add(f"  LEAK SUSPECTS ({len(m['leak_suspects'])} event(s)):")
        for e in m["leak_suspects"]:
            for s in (e.get("suspects") or [])[:8]:
                add(f"    [{e.get('scope')}] {s['shape_class']}: "
                    f"n {s['n_first']} -> {s['n_last']}, "
                    f"+{_fmt_bytes(s['growth_bytes'])} over "
                    f"window {e.get('window')}")
    else:
        add("  leak sentinel: no suspects (green)")
    if m["postmortems"]:
        add(f"  OOM POSTMORTEMS ({len(m['postmortems'])}):")
        for p in m["postmortems"]:
            add(f"    [{p.get('scope')}] program={p.get('program')} "
                + (f"replica={p['replica']} " if p.get("replica") else "")
                + f"error={str(p.get('error'))[:120]}")
            for r in (p.get("ledger") or [])[:4]:
                add(f"      ledger: {r.get('program')} "
                    f"[{r.get('shape_class')}] "
                    f"temp={_fmt_bytes(r.get('temp_bytes'))} "
                    f"total={_fmt_bytes(r.get('total_bytes'))}")
            census = p.get("census")
            if census:
                add(f"      live arrays at death: {census.get('n')} "
                    f"({_fmt_bytes(census.get('bytes'))} across "
                    f"{census.get('classes')} shape class(es))")
            for d in (p.get("snapshot") or [])[:4]:
                if isinstance(d, dict) and d.get("bytes_in_use") is not None:
                    add(f"      device {d.get('id')}: in_use="
                        f"{_fmt_bytes(d['bytes_in_use'])} peak="
                        f"{_fmt_bytes(d.get('peak_bytes_in_use'))} limit="
                        f"{_fmt_bytes(d.get('bytes_limit'))}")
    else:
        add("  OOM postmortems: none")
    return "\n".join(lines)


def render_store(report: Dict[str, Any]) -> str:
    s = report.get("store")
    if not s:
        return "(no store events in the log)"
    lines = ["feature store (ncnet_tpu/store/, replayed from the log):"]
    add = lines.append
    for o in s["opens"]:
        add(f"  open [{o.get('scope')}]: {o.get('entries')} entr(ies), "
            f"{_fmt_bytes(o.get('bytes'))} under {o.get('fingerprint')}"
            + (f", budget {_fmt_bytes(o['budget_bytes'])}"
               if o.get("budget_bytes") else "")
            + f"  ({o.get('state')})")
    for scope, st in sorted(s["final_stats"].items()):
        c = st.get("counters") or {}
        hp = st.get("hit_pct")
        add(f"  final [{scope}]: {st.get('state')}"
            + (f" ({st.get('reason')})" if st.get("reason") else "")
            + f"  hits={c.get('hits', 0)} misses={c.get('misses', 0)}"
            + (f" ({hp:.1f}% hit)" if isinstance(hp, (int, float)) else "")
            + f"  corrupt={c.get('corrupt', 0)} "
            f"evictions={c.get('evictions', 0)} "
            f"degraded_ops={c.get('degraded_ops', 0)}  "
            f"entries={st.get('entries')} "
            f"bytes={_fmt_bytes(st.get('bytes'))}")
    if s["health_timeline"]:
        add(f"  health timeline ({s['degraded_spells']} degraded "
            f"spell(s), {s['recovered']} recover(ies)):")
        for e in s["health_timeline"]:
            add(f"    -> {e.get('state')} [{e.get('scope')}]"
                + (f"  ({e.get('reason')})" if e.get("reason") else ""))
    else:
        add("  health timeline: never degraded (green)")
    if s["corrupt_quarantined"]:
        add(f"  CORRUPT entries quarantined "
            f"({len(s['corrupt_quarantined'])}):")
        for e in s["corrupt_quarantined"]:
            add(f"    {e.get('digest')}  ({e.get('reason')}) -> "
                f"{e.get('quarantined_to')}")
    else:
        add("  corruption: none detected")
    if s["evictions"]:
        add(f"  evictions: {s['evictions']} "
            f"({_fmt_bytes(s['evicted_bytes'])} reclaimed)")
    for g in s["gc_sweeps"]:
        add(f"  GC [{g.get('scope')}]: removed {g.get('entries')} "
            f"entr(ies) of superseded generation(s) {g.get('fingerprints')}")
    return "\n".join(lines)


def render_retrieval(report: Dict[str, Any]) -> str:
    r = report.get("retrieval")
    if not r:
        return "(no retrieval events in the log)"
    lines = ["retrieval tier (replayed from the event log):"]
    o = r["outcomes"]
    ident = "HOLDS" if o["unresolved"] == 0 and not r["lost_requests"] \
        else "VIOLATED"
    lines.append(
        f"  outcomes: admitted={o['admitted']}  results={o['results']} "
        f"(degraded={o['results_degraded']})  "
        f"deadline={o['deadline_exceeded']}  shed={o['shed']}  "
        f"unresolved={o['unresolved']}  [identity {ident}]")
    if r["lost_requests"]:
        lines.append("  LOST requests (admitted, no terminal outcome): "
                     + ", ".join(r["lost_requests"][:10]))
    cov = r["coverage"]
    if cov.get("n"):
        lines.append(
            f"  coverage: p50={cov.get('p50')} p90={cov.get('p90')} "
            f"min={cov.get('min')}  below-full={cov['below_full']} "
            f"of {cov['n']} (degraded or shed, never silent)")
    if r["latency_ms"]:
        lines.append("  sweep wall: "
                     + _fmt_stats(r["latency_ms"], "ms"))
    h = r["hedging"]
    lines.append(
        f"  hedging: {h['hedge_dispatches']} dispatch(es) over "
        f"{h['hedged_queries']} query(ies) "
        f"({h['hedge_rate_pct']}% of results)")
    if r["shards"]:
        lines.append("  per-shard:")
        for sid, s in r["shards"].items():
            err = (" errors=" + ",".join(
                f"{k}:{v}" for k, v in sorted(s["errors"].items()))
                if s["errors"] else "")
            lines.append(
                f"    {sid}: results={s['results']} "
                f"consulted={s['consulted']} "
                f"unavailable={s['unavailable']} deaths={s['deaths']} "
                f"resurrections={s['resurrections']} "
                f"hedges_absorbed={s['hedges_absorbed']}{err}")
    deaths = [t for t in r["timeline"] if t.get("state") == "DEAD"]
    if deaths or any(t.get("state") == "READY" and t.get("shard")
                     for t in r["timeline"]):
        lines.append("  shard lifecycle timeline:")
        for t in r["timeline"]:
            if t.get("shard") is None:
                continue
            lines.append(f"    t={t.get('t')}: {t['shard']} -> "
                         f"{t.get('state')} ({t.get('reason')})")
    il = r.get("inloc_shortlist")
    if il:
        lines.append(
            f"  inloc shortlist: reordered={il['reordered']} "
            f"fallbacks={il['fallbacks']} "
            f"reasons={il['fallback_reasons']}")
    fin = r.get("final_health_doc")
    if fin:
        pod = fin.get("pod", {})
        lines.append(
            f"  final health: {fin.get('state')} "
            f"(shards {pod.get('ready')}/{pod.get('total')})")
    return "\n".join(lines)


def render_rollout(report: Dict[str, Any]) -> str:
    r = report.get("rollout")
    if not r:
        return "(no rollout events in the log)"
    lines = ["rollout (serving/rollout.py, replayed from the event log):"]
    add = lines.append
    if r["phases"]:
        add("  phase timeline:")
        for p in r["phases"]:
            vers = ""
            if p.get("old_version") or p.get("new_version"):
                vers = (f"  [{p.get('old_version')} -> "
                        f"{p.get('new_version')}]")
            add(f"    -> {p.get('phase')}{vers}"
                + (f"  ({p.get('reason')})" if p.get("reason") else ""))
        term = r.get("terminal_phase") or "(none — log ends mid-rollout)"
        add(f"  terminal phase: {term}")
    for f in r["refusals"]:
        add(f"  REFUSED {f.get('candidate')}: {f.get('reason')}"
            + (f"  ({str(f.get('error'))[:120]})" if f.get("error")
               else ""))
    if r["swaps"]:
        add(f"  weight swaps ({len(r['swaps'])}, "
            f"{r['swaps_failed']} failed):")
        for s in r["swaps"]:
            ok = "ok" if s.get("ok") else f"FAILED ({s.get('error')})"
            add(f"    {s.get('replica')} -> {s.get('version')}  "
                f"warmed={s.get('warmed')}  {ok}")
    for v in r["canary_verdicts"]:
        breach = v.get("breach")
        tag = f"BREACH {breach}" if breach else "pass"
        add(f"  canary verdict [{tag}]: {v.get('old_version')} vs "
            f"{v.get('new_version')}  results={v.get('results')}")
        psi = v.get("psi") or {}
        if psi:
            add("    psi: " + ", ".join(
                f"{k}={psi[k]:.4f}" if isinstance(psi[k], (int, float))
                else f"{k}={psi[k]}" for k in sorted(psi))
                + f"  (threshold {v.get('psi_threshold')})")
        if v.get("error_rate"):
            add(f"    error rate: {v['error_rate']}")
        if v.get("latency_ewma_ms"):
            add(f"    latency EWMA (ms): {v['latency_ewma_ms']}")
    for rb in r["rollbacks"]:
        stuck = rb.get("stuck_replicas") or []
        add(f"  ROLLED BACK ({rb.get('reason')}): restored "
            f"{rb.get('old_version')}"
            + (f"  [stuck replicas: {', '.join(map(str, stuck))}]"
               if stuck else ""))
    if r["versions"]:
        add("  per-version accounting (from version-tagged serve "
            "results):")
        for vid, v in r["versions"].items():
            add(f"    {vid}: results={v['results']}  "
                f"failures={v['failures']}"
                + (f"  latency {_fmt_stats(v['latency_ms'], 'ms')}"
                   if v["latency_ms"] else ""))
    if r["replica_drains"]:
        add(f"  replica drains: {len(r['replica_drains'])} "
            "(one at a time is the capacity invariant)")
        for d in r["replica_drains"]:
            add(f"    {d.get('replica')}  ({d.get('reason')})")
    return "\n".join(lines)


def render_slo(report: Dict[str, Any]) -> str:
    s = report.get("slo")
    if not s or not s["admitted"]:
        return "(no admitted serving outcomes in the log)"
    lines = ["SLO / error budget (replayed from the event log):"]
    cfg = s.get("objectives") or {}
    obj = cfg.get("default_ms")
    lines.append(
        f"  objective: {obj if obj is not None else 'none'} ms default"
        + (f", per-bucket {cfg['by_bucket']}" if cfg.get("by_bucket")
           else "")
        + f"; budget {cfg.get('budget_pct', 1.0)}% bad")
    b = s["bad"]
    lines.append(
        f"  outcomes: admitted={s['admitted']}  ok={s['ok']}  "
        f"bad={s['bad_total']} (latency={b['latency']} "
        f"deadline={b['deadline']} quarantined={b['quarantined']} "
        f"shed={b['shed']})")
    lines.append(f"  budget burn: {s['budget_burn_pct']}% "
                 "(100 = budget exactly spent)")
    if "matches_final_event" in s:
        tag = "consistent" if s["matches_final_event"] else "MISMATCH"
        lines.append(
            f"  scrape-vs-replay: {tag} with the service's final slo "
            f"event ({s['slo_events']} slo event(s) in the log)")
        if not s["matches_final_event"]:
            lines.append(f"    final event: {s['final_event']}")
    return "\n".join(lines)


def render_text(report: Dict[str, Any]) -> str:
    lines: List[str] = []
    add = lines.append
    add("=== ncnet_tpu run report ===")
    for lg in report["logs"]:
        h = lg["header"]
        add(f"log: {lg['path']}  (schema {h.get('schema')}, host "
            f"{h.get('host')}, device {h.get('device_kind', 'n/a')})")
    add("")
    add("run lineage:")
    for r in report["lineage"]:
        line = f"  {r['run_id']}  events={r['events']}"
        if "resumed_from" in r:
            rf = r["resumed_from"]
            line += (f"  resumed from step {rf.get('step')} "
                     f"(epoch {rf.get('epoch')}, batch {rf.get('batch')})")
        add(line)
    add("")
    c = report["counts"]
    add(f"steps={c['steps']}  epochs={c['epochs_completed']}  "
        f"checkpoints={c['checkpoint_commits']}  resumes={c['resumes']}")
    add(f"nan_skips={c['nan_skips']}  preemptions={c['preemptions']}  "
        f"quarantines={c['quarantines']}  "
        f"tier_demotions={c['tier_demotions']}  "
        f"watchdog_timeouts={c['watchdog_timeouts']}")
    add("")
    add(f"step wall:   {_fmt_stats(report['step_wall_s'])}")
    add(f"stage wall:  {_fmt_stats(report['stage_wall_s'])}")
    add(f"throughput:  {_fmt_stats(report['pairs_per_s'], ' pairs/s')}")
    traj = report["mfu_trajectory"]
    if traj:
        first, last = traj[0], traj[-1]
        peak = max(traj, key=lambda e: e["mfu_pct"])
        add(f"MFU: first {first['mfu_pct']:.2f}% @ step {first['step']}, "
            f"peak {peak['mfu_pct']:.2f}% @ step {peak['step']}, "
            f"last {last['mfu_pct']:.2f}% @ step {last['step']}")
    if report["tier_timeline"]:
        add("")
        add("tier timeline:")
        for e in report["tier_timeline"]:
            if e["event"] == "tier_demoted":
                add(f"  DEMOTED {e.get('tier')}  "
                    f"(now disabled: {e.get('demoted')})")
            else:
                add(f"  selected {e.get('tier')} for {e.get('stage')} "
                    f"shape {e.get('shape')}")
    if report["retries_by_kind"]:
        add("")
        add("retries by kind: " + ", ".join(
            f"{k}={v}" for k, v in sorted(report["retries_by_kind"].items())))
    if report["quarantines"]:
        add("")
        add("quarantined units:")
        for qn in report["quarantines"]:
            add(f"  {qn['unit']}  kind={qn['kind']} "
                f"attempts={qn.get('attempts')}")
    pm = report["divergence_postmortem"]
    if pm:
        add("")
        add(f"DIVERGED at step {pm['died_at_step']} (epoch {pm['epoch']}, "
            f"streak {pm['streak']}); last steps:")
        for e in pm["last_steps"]:
            add(f"  step {e.get('step')}: loss={e.get('loss')} "
                f"grad_norm={e.get('grad_norm')}")
    ev = report.get("eval")
    if ev:
        add("")
        add(f"eval: batches={ev['batches']} queries={ev['queries']} "
            f"(ok={ev['queries_ok']})")
        if ev["batch_pck"]:
            add(f"  batch PCK: {_fmt_stats(ev['batch_pck'], '')}")
        for s in ev["summaries"]:
            m = s.get("metrics", {})
            add("  summary: " + json.dumps(
                {k: m[k] for k in sorted(m) if not isinstance(m[k], dict)}))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Replay ncnet_tpu event logs into a run report")
    ap.add_argument("logs", nargs="+", help="events.jsonl file(s)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as one JSON document")
    ap.add_argument("--spans", action="store_true",
                    help="append the span critical-path breakdown "
                         "(self-time vs child-time per phase)")
    ap.add_argument("--quality", action="store_true",
                    help="append the match-quality section: per-tier "
                         "signal table, drift verdicts vs the committed "
                         "reference, signal-vs-PCK rank correlation")
    ap.add_argument("--quality-ref", default=None,
                    help="reference distributions for the drift verdicts "
                         "(default: perf/quality_ref.jsonl)")
    ap.add_argument("--serving", action="store_true",
                    help="append the serving section: request-outcome "
                         "accounting (the outcome-total invariant), "
                         "per-bucket latency, queue-depth trajectory, "
                         "health-state timeline — plus the router section "
                         "(backend-tagged accounting, the outcome-total "
                         "identity recomputed at the router level) when "
                         "the log holds route_* events")
    ap.add_argument("--rollout", action="store_true",
                    help="append the rollout section: phase timeline, "
                         "per-replica weight swaps, canary verdicts (PSI/"
                         "error-rate/latency), rollbacks, refusals, and "
                         "per-model-version request accounting replayed "
                         "from rollout_* and version-tagged serve events")
    ap.add_argument("--memory", action="store_true",
                    help="append the memory section: the compiled-program "
                         "ledger table, the HBM trajectory, leak-sentinel "
                         "verdicts, and OOM postmortems — all replayed "
                         "from the event log alone")
    ap.add_argument("--slo", action="store_true",
                    help="append the SLO section: error-budget counters "
                         "recomputed from the log (objectives from "
                         "serve_start), burn %%, and the consistency "
                         "verdict against the service's final slo event")
    ap.add_argument("--retrieval", action="store_true",
                    help="append the retrieval-tier section: the "
                         "coordinator outcome-total identity, the coverage "
                         "distribution, hedge rate, per-shard outcome "
                         "accounting, and the shard death/resurrection "
                         "timeline replayed from retrieve_* events")
    ap.add_argument("--pod", action="store_true",
                    help="append the pod section: the outcome-total "
                         "identity recomputed ACROSS all given logs at "
                         "once, trace-joined — edge totality, router-to-"
                         "backend trail continuity (dark trails named), "
                         "failover/hedge attribution by trace, and the "
                         "edge-minus-backend wall = wire+routing overhead")
    ap.add_argument("--store", action="store_true",
                    help="append the feature-store section: hit/miss/"
                         "corrupt/evict counters, the DEGRADED->recovered "
                         "health timeline, quarantined entries, and GC "
                         "sweeps replayed from the event log")
    args = ap.parse_args(argv)
    quality_ref = None
    if args.quality or args.quality_ref:
        from ncnet_tpu.observability.quality import default_reference_path

        quality_ref = args.quality_ref or default_reference_path()
    report = build_report(args.logs, quality_ref=quality_ref)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render_text(report))
        if args.spans:
            print()
            print(render_spans(report))
        if args.quality:
            print()
            print(render_quality(report))
        if args.serving:
            print()
            print(render_serving(report))
            if report.get("router"):
                print()
                print(render_router(report))
        if args.rollout:
            print()
            print(render_rollout(report))
        if args.memory:
            print()
            print(render_memory(report))
        if args.pod:
            print()
            print(render_pod(report))
        if args.retrieval:
            print()
            print(render_retrieval(report))
        if args.slo:
            print()
            print(render_slo(report))
        if args.store:
            print()
            print(render_store(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
