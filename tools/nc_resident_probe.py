#!/usr/bin/env python
"""Parity + composed timing of the RESIDENT fused NC stack vs the per-layer
chain and the XLA stack, with layer-prefix differencing for attribution.

Usage: python tools/nc_resident_probe.py [batch_volumes]

Run on a TPU backend: the resident tier needs Mosaic (parity on CPU is
covered by interpret-mode tests).  This is the measurement companion of
ops/nc_fused_lane.py's round-6 resident kernel — the per-stage numbers here
are what the bench's filter_stage_* extras automate.
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")

from _timing import timeit  # noqa: E402

B = int(sys.argv[1]) if len(sys.argv) > 1 else 8
S = 25
DT = jnp.bfloat16


def make_params(ks):
    chans = [(1, 16), (16, 16), (16, 1)]
    params = []
    for kk, (ci, co) in zip(ks, chans):
        k1, k2, kk2 = jax.random.split(kk, 3)
        params.append({
            "w": jax.random.normal(k1, (5, 5, 5, 5, ci, co), DT) * 0.05,
            "b": jax.random.normal(k2, (co,), DT) * 0.1,
        })
    return params


def xla_stack(params, x):
    from ncnet_tpu.ops.conv4d import conv4d

    for layer in params:
        x = jax.nn.relu(conv4d(x, layer["w"], layer["b"]))
    return x


def main():
    from ncnet_tpu.ops.nc_fused_lane import (
        fused_resident_compiles,
        fused_resident_feasible,
        nc_stack_fused_lane,
        nc_stack_resident,
    )

    print(f"device={jax.devices()[0].device_kind} n_volumes={B}")
    print("feasible:",
          fused_resident_feasible(S, S, S, S, (5, 5, 5), (16, 16, 1)))
    print("compiles:",
          fused_resident_compiles(S, S, S, S, (5, 5, 5), (16, 16, 1)))

    key = jax.random.key(0)
    params = make_params(jax.random.split(key, 3))
    x = jax.random.normal(jax.random.key(9), (2, S, S, S, S, 1), DT) * 0.1

    ref = np.asarray(jax.jit(xla_stack)(params, x), np.float32)
    got = np.asarray(jax.jit(nc_stack_resident)(params, x), np.float32)
    err = np.max(np.abs(got - ref))
    rel = err / max(1e-6, float(np.max(np.abs(ref))))
    print(f"parity: max abs err {err:.4g} (rel {rel:.3%})")
    assert rel < 0.05

    def make_input(key):
        k1, *ks = jax.random.split(key, 4)
        return (
            jax.random.normal(k1, (B, S, S, S, S, 1), DT) * 0.1,
            make_params(ks),
        )

    def step_of(fn):
        def step(carry):
            x, params = carry
            out = fn(params, x)
            eps = (jnp.sum(out.astype(jnp.float32)) * 1e-12).astype(x.dtype)
            return x + eps, params
        return step

    ms_r = timeit(step_of(nc_stack_resident), make_input, per=B, n_long=8)
    ms_p = timeit(step_of(nc_stack_fused_lane), make_input, per=B, n_long=8)
    ms_x = timeit(step_of(xla_stack), make_input, per=B, n_long=8)
    print(f"resident stack : {ms_r:7.3f} ms/volume")
    print(f"per-layer chain: {ms_p:7.3f} ms/volume")
    print(f"xla stack      : {ms_x:7.3f} ms/volume")

    # layer-prefix differencing on the resident kernel (wide-final probe
    # relaxation for the truncated chains)
    prev = 0.0
    for n in (1, 2, 3):
        def fn(params, x, n=n):
            return nc_stack_resident(params[:n], x, _allow_wide_final=True)

        t = timeit(step_of(fn), make_input, per=B, n_long=8)
        print(f"prefix[:{n}]    : {t:7.3f} ms/volume  (+{t - prev:6.3f})")
        prev = t


if __name__ == "__main__":
    main()
