#!/usr/bin/env python
"""(Re)generate the frozen-weight activation goldens in tests/goldens/.

The released reference weights are unreachable in this environment (zero
egress — datasets/trained_models download URLs resolve nowhere), so drift
detection uses *self-goldens*: fixed deterministic weights + fixed inputs →
recorded outputs.  Any change to backbone/conv4d/correlation/mutual-matching
numerics across commits shows up as a golden mismatch (SURVEY §4 "Golden").

Run from the repo root ON CPU (the CI platform):
    JAX_PLATFORM_NAME=cpu python tools/make_goldens.py
Regenerate ONLY when a numerics change is intended, and say so in the commit.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def deterministic_params(cfg):
    """Params from a numpy RNG (stable across jax versions, unlike jax PRNG)."""
    import jax
    from ncnet_tpu.models.ncnet import init_ncnet

    shapes = jax.eval_shape(lambda: init_ncnet(cfg, jax.random.key(0)))
    rng = np.random.default_rng(1234)

    def fill(path, leaf):
        vals = (rng.standard_normal(leaf.shape) * 0.05).astype(leaf.dtype)
        # BN running variance must stay positive or sqrt(var + eps) NaNs out
        if any(getattr(p, "key", None) == "var" for p in path):
            vals = np.abs(vals) + 0.1
        return vals

    return jax.tree_util.tree_map_with_path(fill, shapes)


def canonical_p3p_order(sols: np.ndarray) -> np.ndarray:
    """NaN-mask and lexicographically sort each sample's ≤4 candidate poses.

    p3p_solve fills its solution slots in companion-matrix ``eigvals`` order,
    which is LAPACK-implementation-defined — comparing slots positionally
    would raise false drift alarms across BLAS builds.  Shared by the golden
    generator and the golden test."""
    masked = np.nan_to_num(np.asarray(sols, dtype=np.float64), nan=-1e9)
    out = []
    for sample in masked:
        rows = sorted(sample.reshape(sample.shape[0], -1).tolist())
        out.append(np.asarray(rows).reshape(sample.shape))
    return np.stack(out)


def main():
    import warnings

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from ncnet_tpu.config import ModelConfig
    from ncnet_tpu.models.ncnet import ncnet_forward
    from ncnet_tpu.ops import corr_to_matches

    out_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests", "goldens")
    os.makedirs(out_dir, exist_ok=True)

    rng = np.random.default_rng(7)
    record = {}

    # 1. full forward, tiny trunk, rectangular pair, relocalization k=2
    cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3, 3),
                      ncons_channels=(8, 1), relocalization_k_size=2)
    params = deterministic_params(cfg)
    src = rng.uniform(-1, 1, (1, 64, 96, 3)).astype(np.float32)
    tgt = rng.uniform(-1, 1, (1, 96, 64, 3)).astype(np.float32)
    out = ncnet_forward(cfg, params, jnp.asarray(src), jnp.asarray(tgt))
    record["tiny_src"] = src
    record["tiny_tgt"] = tgt
    record["tiny_corr"] = np.asarray(out.corr)
    for i, d in enumerate(out.delta4d):
        record[f"tiny_delta{i}"] = np.asarray(d)
    m = corr_to_matches(out.corr, delta4d=out.delta4d, k_size=2,
                        do_softmax=True, scale="positive")
    record["tiny_matches"] = np.stack(
        [np.asarray(v) for v in (m.xA, m.yA, m.xB, m.yB, m.score)])

    # 2. resnet101 trunk features (random but deterministic weights):
    #    catches drift in the conv/BN/L2-norm stack
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # intentional random trunk
        cfg_r = ModelConfig(backbone="resnet101", ncons_kernel_sizes=(3,),
                            ncons_channels=(1,))
        params_r = deterministic_params(cfg_r)
    from ncnet_tpu.models.ncnet import extract_features

    img = rng.uniform(-1, 1, (1, 96, 96, 3)).astype(np.float32)
    feats = np.asarray(extract_features(cfg_r, params_r, jnp.asarray(img)))
    record["resnet_img"] = img
    record["resnet_feat_mean"] = feats.mean(axis=-1)        # (1, 6, 6)
    record["resnet_feat_slice"] = feats[0, :, :, :8]        # (6, 6, 8)

    # 3. localization numerics: dense-SIFT descriptors and a P3P pose on
    #    fixed inputs — guards the descriptor pipeline and the Grunert
    #    quartic + Kabsch chain against cross-round drift
    from ncnet_tpu.localization.dsift import dense_sift, rootsift
    from ncnet_tpu.localization.p3p import p3p_solve

    img = rng.random((72, 88)).astype(np.float32)
    desc = rootsift(dense_sift(img))
    record["dsift_img"] = img
    record["dsift_desc_sample"] = desc[::3, ::3, :16]
    record["dsift_desc_mean"] = desc.mean(axis=-1)

    rays = rng.normal(size=(4, 3, 3))
    rays /= np.linalg.norm(rays, axis=2, keepdims=True)
    pts = rng.uniform(-1, 1, (4, 3, 3)) + np.array([0.0, 0.0, 4.0])
    sols = p3p_solve(rays, pts)
    record["p3p_rays"] = rays
    record["p3p_pts"] = pts
    # NaN slots masked + slots canonically ordered (eigvals order is
    # LAPACK-implementation-defined)
    record["p3p_solutions"] = canonical_p3p_order(sols)

    path = os.path.join(out_dir, "activations.npz")
    np.savez_compressed(path, **record)
    print(f"wrote {path} ({os.path.getsize(path) / 1024:.0f} KiB)")


if __name__ == "__main__":
    main()
