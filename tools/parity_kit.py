#!/usr/bin/env python
"""Real-weights parity kit: one command from released checkpoint to evidence.

The repo's numerics are locked by self-goldens and synthetic oracles
(tests/test_goldens.py) because this rig has no egress to fetch the released
``ncnet_pfpascal.pth.tar`` or the PF-Pascal images (VERDICT r2 "Missing #2").
This script packages the missing external validation so that the moment
weights + data are reachable, the parity claim is one command away:

  1. PCK on real data (the reference's de-facto quality bar,
     /root/reference/eval_pf_pascal.py:84-89):

        python tools/parity_kit.py \
            --torch_checkpoint trained_models/ncnet_pfpascal.pth.tar \
            --dataset datasets/pf-pascal

  2. Per-stage trace for cross-framework diffing:

        python tools/parity_kit.py --torch_checkpoint ... --dataset ... \
            --record_trace ours.npz [--pairs 5]

     writes, for each of the first N test pairs, arrays named
     ``<stage>_<i>``: ``feature_A`` / ``feature_B`` (L2-normed backbone
     features, NHWC), ``corr_raw`` (4D correlation, (1,hA,wA,hB,wB)),
     ``corr_filtered`` (after MutualMatching→NC→MutualMatching), and
     ``matches`` ((5,N): xA,yA,xB,yB,score from corr_to_matches with
     softmax, B→A direction).

  3. Diff two traces (ours vs one recorded from the reference PyTorch
     implementation — record the same stages from ImMatchNet's forward,
     lib/model.py:261-282, transposing NCHW features to NHWC and the
     (B,1,hA,wA,hB,wB) volume to (B,hA,wA,hB,wB)):

        python tools/parity_kit.py --compare ours.npz theirs.npz

     prints per-stage max-abs-diff and fails (exit 1) above --tolerance.

  4. THE real-weights-day runbook — everything above as one command:

        python tools/parity_kit.py --all \
            --pfpascal_checkpoint trained_models/ncnet_pfpascal.pth.tar \
            --ivd_checkpoint trained_models/ncnet_ivd.pth.tar \
            --dataset datasets/pf-pascal

     Per checkpoint: imports it through the production loader (incl. the
     legacy 'vgg'→'model' rekey and arch-override-from-args,
     models/checkpoint.py), prints the recovered architecture, and
     GOLDEN-CHECKS ACTIVATIONS by driving the in-repo torch twin of the
     reference's entire forward (tests/test_torch_parity.py) with the SAME
     checkpoint weights on a fixed synthetic pair — a cross-framework
     activation check that needs only this image's torch, no egress.  With
     ``--dataset``, runs the full PF-Pascal eval on the pfpascal checkpoint
     and prints PCK@0.1 against the reference-reported ⚠ 78.9% target
     (BASELINE.md; ⚠ = reported by the paper, never reproduced in this
     offline rig).  Exit 1 if any activation check exceeds tolerance or,
     when ``--expect_pck`` is given, PCK lands below it.

Tested end-to-end against a synthetically written ``.pth.tar`` in
tests/test_parity_kit.py (the importer path is models/checkpoint.py).
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_net(torch_checkpoint: str):
    from ncnet_tpu.config import ModelConfig
    from ncnet_tpu.models import NCNet

    return NCNet(ModelConfig(checkpoint=torch_checkpoint))


def run_pck(net, dataset: str, image_size: int, progress: bool) -> dict:
    from ncnet_tpu.config import EvalPFPascalConfig
    from ncnet_tpu.evaluation.pf_pascal import run_eval

    cfg = EvalPFPascalConfig(
        eval_dataset_path=dataset, image_size=image_size,
    )
    return run_eval(cfg, net=net, progress=progress)


def record_trace(net, dataset: str, image_size: int, out_path: str,
                 n_pairs: int) -> None:
    import jax
    import jax.numpy as jnp

    from ncnet_tpu.data import PFPascalDataset
    from ncnet_tpu.models.ncnet import extract_features, ncnet_filter
    from ncnet_tpu.ops import corr_to_matches, correlation_4d

    config, params = net.config, net.params

    @jax.jit
    def stages(src, tgt):
        fa = extract_features(config, params, src)
        fb = extract_features(config, params, tgt)
        if config.half_precision:
            fa16, fb16 = fa.astype(jnp.bfloat16), fb.astype(jnp.bfloat16)
        else:
            fa16, fb16 = fa, fb
        corr = correlation_4d(fa16, fb16)
        out = ncnet_filter(config, params, corr)
        m = corr_to_matches(out.corr.astype(jnp.float32), do_softmax=True)
        return {
            "feature_A": fa, "feature_B": fb,
            "corr_raw": corr.astype(jnp.float32),
            "corr_filtered": out.corr.astype(jnp.float32),
            "matches": jnp.stack([m.xA, m.yA, m.xB, m.yB, m.score])[:, 0],
        }

    ds = PFPascalDataset(
        csv_file=f"{dataset.rstrip('/')}/image_pairs/test_pairs.csv",
        dataset_path=dataset,
        output_size=(image_size, image_size),
        pck_procedure="scnet",
    )
    arrays = {}
    for i in range(min(n_pairs, len(ds))):
        sample = ds[i]
        got = stages(
            jnp.asarray(sample["source_image"][None]),
            jnp.asarray(sample["target_image"][None]),
        )
        for k, v in got.items():
            arrays[f"{k}_{i}"] = np.asarray(v)
    np.savez_compressed(out_path, **arrays)
    print(f"recorded {len(arrays)} arrays "
          f"({min(n_pairs, len(ds))} pairs) to {out_path}")


def compare_traces(ours_path: str, theirs_path: str, tolerance: float,
                   allow_missing: bool = False) -> int:
    ours = np.load(ours_path)
    theirs = np.load(theirs_path)
    common = sorted(set(ours.files) & set(theirs.files))
    if not common:
        print(f"no common arrays between {ours_path} and {theirs_path}")
        return 1
    missing = sorted(set(ours.files) ^ set(theirs.files))
    if missing:
        print(f"{len(missing)} arrays present in only one trace: "
              f"{missing[:6]}{'...' if len(missing) > 6 else ''}")
        if not allow_missing:
            # a truncated trace must not read as a confirmed parity claim
            print("FAIL: traces cover different stages "
                  "(pass --allow_missing to diff the intersection only)")
            return 1
    worst = 0.0
    for k in common:
        a, b = ours[k], theirs[k]
        if a.shape != b.shape:
            print(f"{k:>20}: SHAPE MISMATCH {a.shape} vs {b.shape}")
            worst = float("inf")
            continue
        d = float(np.max(np.abs(a.astype(np.float64) - b.astype(np.float64)))) \
            if a.size else 0.0
        rel = d / (float(np.max(np.abs(b))) + 1e-12)
        print(f"{k:>20}: max_abs_diff {d:.3e}   rel {rel:.3e}")
        worst = max(worst, d)
    print(f"worst max_abs_diff: {worst:.3e} (tolerance {tolerance:g})")
    return 0 if worst <= tolerance else 1


def torch_twin_activation_check(torch_checkpoint: str, net,
                                image_size: int = 96,
                                tolerance: float = 2e-3) -> bool:
    """Drive the in-repo torch twin of the reference's ENTIRE forward with
    the checkpoint's own weights and compare against our jitted forward on
    a fixed synthetic pair.  Returns True on agreement within tolerance.

    The twin (tests/test_torch_parity.py) restates the reference semantics
    — resnet101[:layer3] trunk, bmm correlation, MutualMatching, the
    conv4d-as-loop kernel, stack symmetry — so agreement here checks the
    IMPORT (both weight layouts) and the composition at real weights."""
    import torch

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))
    from test_torch_parity import torch_full_forward

    import jax.numpy as jnp

    from ncnet_tpu.models.checkpoint import split_reference_state_dict
    from ncnet_tpu.models.ncnet import ncnet_forward

    if net.config.backbone != "resnet101" or \
            net.config.backbone_last_layer not in ("", "layer3"):
        print("  twin check skipped: the torch twin covers the reference's "
              f"resnet101[:layer3] trunk, checkpoint has "
              f"{net.config.backbone}[{net.config.backbone_last_layer}]")
        return True
    if not os.path.isfile(torch_checkpoint):
        # the rest of the kit accepts orbax checkpoint DIRECTORIES too;
        # the twin needs the reference's torch state_dict layout
        print("  twin check skipped: not a torch .pth.tar "
              "(orbax checkpoints have no reference-layout state_dict)")
        return True

    ckpt = torch.load(torch_checkpoint, map_location="cpu",
                      weights_only=False)
    # the SAME parsing the production importer uses (rekey, trunk split,
    # NC enumeration) — only the final layout permutes differ per consumer
    trunk_sd, nc_raw = split_reference_state_dict(
        ckpt["state_dict"], net.config)
    # stored Conv4d layout (kA, C_out, C_in, kWA, kB, kWB)
    # (/root/reference/lib/conv4d.py:72-77) → twin's conv3d-loop layout
    # (C_out, C_in, kA, kWA, kB, kWB)
    nc_layers = [
        (torch.from_numpy(np.ascontiguousarray(
            np.transpose(w, (1, 2, 0, 3, 4, 5)))), torch.from_numpy(b))
        for w, b in nc_raw
    ]

    rng = np.random.default_rng(11)
    src = rng.standard_normal((1, 3, image_size, image_size)).astype(
        np.float32) * 0.4
    tgt = rng.standard_normal((1, 3, image_size, image_size)).astype(
        np.float32) * 0.4
    with torch.no_grad():
        ref = torch_full_forward(
            trunk_sd, nc_layers, torch.from_numpy(src), torch.from_numpy(tgt)
        )[:, 0].numpy()

    cfg32 = net.config.replace(half_precision=False, backbone_bf16=False,
                               relocalization_k_size=1)
    ours = np.asarray(ncnet_forward(
        cfg32, net.params,
        jnp.asarray(np.transpose(src, (0, 2, 3, 1))),
        jnp.asarray(np.transpose(tgt, (0, 2, 3, 1))),
    ).corr, np.float32)
    diff = float(np.max(np.abs(ours - ref)))
    scale = float(np.max(np.abs(ref))) + 1e-12
    ok = diff / scale <= tolerance
    print(f"  twin activation check: max_abs_diff {diff:.3e} "
          f"(rel {diff / scale:.3e}) vs tolerance {tolerance:g} → "
          f"{'PASS' if ok else 'FAIL'}")
    return ok


def run_all(args) -> int:
    """The --all runbook; see the module docstring item 4."""
    failed = False
    pck_ran = False
    for label, ckpt_path in (("pfpascal", args.pfpascal_checkpoint),
                             ("ivd", args.ivd_checkpoint)):
        if not ckpt_path:
            print(f"[{label}] no checkpoint given — skipped")
            continue
        print(f"[{label}] importing {ckpt_path}")
        net = build_net(ckpt_path)
        last = net.config.backbone_last_layer or (
            "layer3" if net.config.backbone == "resnet101" else "default-cut"
        )
        print(f"  arch: backbone={net.config.backbone}[{last}] "
              f"ncons_kernel_sizes={list(net.config.ncons_kernel_sizes)} "
              f"ncons_channels={list(net.config.ncons_channels)}")
        if not torch_twin_activation_check(ckpt_path, net,
                                           tolerance=args.twin_tolerance):
            failed = True
        if label == "pfpascal" and args.dataset:
            res = run_pck(net, args.dataset, args.image_size,
                          progress=not args.quiet)
            pck_ran = True
            print(f"  PCK@0.1: {res['pck'] * 100:.2f}%  "
                  f"({res['valid']}/{res['total']} valid pairs)  "
                  f"[reference-reported target: ⚠ 78.9%, BASELINE.md]")
            if args.expect_pck is not None and \
                    res["pck"] * 100 < args.expect_pck:
                print(f"  FAIL: PCK below --expect_pck {args.expect_pck}")
                failed = True
        elif label == "pfpascal":
            print("  PCK skipped: pass --dataset <pf-pascal root> to run it")
    if args.expect_pck is not None and not pck_ran:
        # the requested gate must not silently pass un-evaluated
        print("FAIL: --expect_pck given but the PCK eval never ran "
              "(need the pfpascal checkpoint AND --dataset)")
        failed = True
    return 1 if failed else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--torch_checkpoint", help=".pth.tar (or orbax dir)")
    p.add_argument("--dataset", help="PF-Pascal root (images + image_pairs/)")
    p.add_argument("--image_size", type=int, default=400)
    p.add_argument("--record_trace", metavar="OUT_NPZ",
                   help="record per-stage outputs instead of running PCK")
    p.add_argument("--pairs", type=int, default=5,
                   help="pairs to trace with --record_trace")
    p.add_argument("--compare", nargs=2, metavar=("OURS", "THEIRS"),
                   help="diff two trace files; no model/data needed")
    p.add_argument("--tolerance", type=float, default=1e-2,
                   help="max allowed per-stage abs diff for --compare")
    p.add_argument("--allow_missing", action="store_true",
                   help="--compare: diff only the intersection instead of "
                        "failing when the traces cover different stages")
    p.add_argument("--quiet", action="store_true")
    p.add_argument("--all", action="store_true",
                   help="the full real-weights-day runbook (docstring item 4)")
    _CKPT_DEFAULTS = {
        "pfpascal_checkpoint": "trained_models/ncnet_pfpascal.pth.tar",
        "ivd_checkpoint": "trained_models/ncnet_ivd.pth.tar",
    }
    p.add_argument("--pfpascal_checkpoint",
                   default=_CKPT_DEFAULTS["pfpascal_checkpoint"],
                   help="--all: released PF-Pascal checkpoint")
    p.add_argument("--ivd_checkpoint",
                   default=_CKPT_DEFAULTS["ivd_checkpoint"],
                   help="--all: released IVD checkpoint")
    p.add_argument("--twin_tolerance", type=float, default=2e-3,
                   help="--all: relative tolerance of the torch-twin "
                        "activation check")
    p.add_argument("--expect_pck", type=float, default=None,
                   help="--all: fail (exit 1) when PCK%% lands below this")
    args = p.parse_args(argv)

    if args.all:
        for a, default in _CKPT_DEFAULTS.items():
            path = getattr(args, a)
            if path and not os.path.exists(path):
                if path != default:
                    # an EXPLICIT path that doesn't exist is a typo, not a
                    # skip — silently blanking it would let the runbook
                    # exit 0 without testing the named checkpoint
                    p.error(f"--{a} {path}: file not found")
                setattr(args, a, "")
        if not args.pfpascal_checkpoint and not args.ivd_checkpoint:
            p.error("--all: no checkpoint found; pass --pfpascal_checkpoint "
                    "/ --ivd_checkpoint (run trained_models/download.sh "
                    "first on a rig with egress)")
        return run_all(args)
    if args.compare:
        return compare_traces(args.compare[0], args.compare[1], args.tolerance,
                              allow_missing=args.allow_missing)
    if not args.torch_checkpoint or not args.dataset:
        p.error("--torch_checkpoint and --dataset are required "
                "(unless using --compare)")
    net = build_net(args.torch_checkpoint)
    if args.record_trace:
        record_trace(net, args.dataset, args.image_size, args.record_trace,
                     args.pairs)
        return 0
    res = run_pck(net, args.dataset, args.image_size,
                  progress=not args.quiet)
    print(f"PCK: {res['pck']:.4f}  ({res['valid']}/{res['total']} valid pairs)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
