#!/usr/bin/env python
"""Real-weights parity kit: one command from released checkpoint to evidence.

The repo's numerics are locked by self-goldens and synthetic oracles
(tests/test_goldens.py) because this rig has no egress to fetch the released
``ncnet_pfpascal.pth.tar`` or the PF-Pascal images (VERDICT r2 "Missing #2").
This script packages the missing external validation so that the moment
weights + data are reachable, the parity claim is one command away:

  1. PCK on real data (the reference's de-facto quality bar,
     /root/reference/eval_pf_pascal.py:84-89):

        python tools/parity_kit.py \
            --torch_checkpoint trained_models/ncnet_pfpascal.pth.tar \
            --dataset datasets/pf-pascal

  2. Per-stage trace for cross-framework diffing:

        python tools/parity_kit.py --torch_checkpoint ... --dataset ... \
            --record_trace ours.npz [--pairs 5]

     writes, for each of the first N test pairs, arrays named
     ``<stage>_<i>``: ``feature_A`` / ``feature_B`` (L2-normed backbone
     features, NHWC), ``corr_raw`` (4D correlation, (1,hA,wA,hB,wB)),
     ``corr_filtered`` (after MutualMatching→NC→MutualMatching), and
     ``matches`` ((5,N): xA,yA,xB,yB,score from corr_to_matches with
     softmax, B→A direction).

  3. Diff two traces (ours vs one recorded from the reference PyTorch
     implementation — record the same stages from ImMatchNet's forward,
     lib/model.py:261-282, transposing NCHW features to NHWC and the
     (B,1,hA,wA,hB,wB) volume to (B,hA,wA,hB,wB)):

        python tools/parity_kit.py --compare ours.npz theirs.npz

     prints per-stage max-abs-diff and fails (exit 1) above --tolerance.

Tested end-to-end against a synthetically written ``.pth.tar`` in
tests/test_parity_kit.py (the importer path is models/checkpoint.py).
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_net(torch_checkpoint: str):
    from ncnet_tpu.config import ModelConfig
    from ncnet_tpu.models import NCNet

    return NCNet(ModelConfig(checkpoint=torch_checkpoint))


def run_pck(net, dataset: str, image_size: int, progress: bool) -> dict:
    from ncnet_tpu.config import EvalPFPascalConfig
    from ncnet_tpu.evaluation.pf_pascal import run_eval

    cfg = EvalPFPascalConfig(
        eval_dataset_path=dataset, image_size=image_size,
    )
    return run_eval(cfg, net=net, progress=progress)


def record_trace(net, dataset: str, image_size: int, out_path: str,
                 n_pairs: int) -> None:
    import jax
    import jax.numpy as jnp

    from ncnet_tpu.data import PFPascalDataset
    from ncnet_tpu.models.ncnet import extract_features, ncnet_filter
    from ncnet_tpu.ops import corr_to_matches, correlation_4d

    config, params = net.config, net.params

    @jax.jit
    def stages(src, tgt):
        fa = extract_features(config, params, src)
        fb = extract_features(config, params, tgt)
        if config.half_precision:
            fa16, fb16 = fa.astype(jnp.bfloat16), fb.astype(jnp.bfloat16)
        else:
            fa16, fb16 = fa, fb
        corr = correlation_4d(fa16, fb16)
        out = ncnet_filter(config, params, corr)
        m = corr_to_matches(out.corr.astype(jnp.float32), do_softmax=True)
        return {
            "feature_A": fa, "feature_B": fb,
            "corr_raw": corr.astype(jnp.float32),
            "corr_filtered": out.corr.astype(jnp.float32),
            "matches": jnp.stack([m.xA, m.yA, m.xB, m.yB, m.score])[:, 0],
        }

    ds = PFPascalDataset(
        csv_file=f"{dataset.rstrip('/')}/image_pairs/test_pairs.csv",
        dataset_path=dataset,
        output_size=(image_size, image_size),
        pck_procedure="scnet",
    )
    arrays = {}
    for i in range(min(n_pairs, len(ds))):
        sample = ds[i]
        got = stages(
            jnp.asarray(sample["source_image"][None]),
            jnp.asarray(sample["target_image"][None]),
        )
        for k, v in got.items():
            arrays[f"{k}_{i}"] = np.asarray(v)
    np.savez_compressed(out_path, **arrays)
    print(f"recorded {len(arrays)} arrays "
          f"({min(n_pairs, len(ds))} pairs) to {out_path}")


def compare_traces(ours_path: str, theirs_path: str, tolerance: float,
                   allow_missing: bool = False) -> int:
    ours = np.load(ours_path)
    theirs = np.load(theirs_path)
    common = sorted(set(ours.files) & set(theirs.files))
    if not common:
        print(f"no common arrays between {ours_path} and {theirs_path}")
        return 1
    missing = sorted(set(ours.files) ^ set(theirs.files))
    if missing:
        print(f"{len(missing)} arrays present in only one trace: "
              f"{missing[:6]}{'...' if len(missing) > 6 else ''}")
        if not allow_missing:
            # a truncated trace must not read as a confirmed parity claim
            print("FAIL: traces cover different stages "
                  "(pass --allow_missing to diff the intersection only)")
            return 1
    worst = 0.0
    for k in common:
        a, b = ours[k], theirs[k]
        if a.shape != b.shape:
            print(f"{k:>20}: SHAPE MISMATCH {a.shape} vs {b.shape}")
            worst = float("inf")
            continue
        d = float(np.max(np.abs(a.astype(np.float64) - b.astype(np.float64)))) \
            if a.size else 0.0
        rel = d / (float(np.max(np.abs(b))) + 1e-12)
        print(f"{k:>20}: max_abs_diff {d:.3e}   rel {rel:.3e}")
        worst = max(worst, d)
    print(f"worst max_abs_diff: {worst:.3e} (tolerance {tolerance:g})")
    return 0 if worst <= tolerance else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--torch_checkpoint", help=".pth.tar (or orbax dir)")
    p.add_argument("--dataset", help="PF-Pascal root (images + image_pairs/)")
    p.add_argument("--image_size", type=int, default=400)
    p.add_argument("--record_trace", metavar="OUT_NPZ",
                   help="record per-stage outputs instead of running PCK")
    p.add_argument("--pairs", type=int, default=5,
                   help="pairs to trace with --record_trace")
    p.add_argument("--compare", nargs=2, metavar=("OURS", "THEIRS"),
                   help="diff two trace files; no model/data needed")
    p.add_argument("--tolerance", type=float, default=1e-2,
                   help="max allowed per-stage abs diff for --compare")
    p.add_argument("--allow_missing", action="store_true",
                   help="--compare: diff only the intersection instead of "
                        "failing when the traces cover different stages")
    p.add_argument("--quiet", action="store_true")
    args = p.parse_args(argv)

    if args.compare:
        return compare_traces(args.compare[0], args.compare[1], args.tolerance,
                              allow_missing=args.allow_missing)
    if not args.torch_checkpoint or not args.dataset:
        p.error("--torch_checkpoint and --dataset are required "
                "(unless using --compare)")
    net = build_net(args.torch_checkpoint)
    if args.record_trace:
        record_trace(net, args.dataset, args.image_size, args.record_trace,
                     args.pairs)
        return 0
    res = run_pck(net, args.dataset, args.image_size,
                  progress=not args.quiet)
    print(f"PCK: {res['pck']:.4f}  ({res['valid']}/{res['total']} valid pairs)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
