#!/usr/bin/env python
"""Split the symmetric NC stack's backward cost into dx vs dw.

  nc_fwd     forward only
  nc_dx      grad w.r.t. the input volume, params stopped  (dx chain x3)
  nc_dw      grad w.r.t. params                            (dw x3 + dx x2)
  nc_both    grad w.r.t. both

Usage: python tools/nc_grad_split_probe.py [batch] [dtype]
"""

import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")

from _timing import timeit  # noqa: E402

from ncnet_tpu.models.ncnet import neigh_consensus  # noqa: E402
from ncnet_tpu.ops import conv4d_init, correlation_4d  # noqa: E402
from ncnet_tpu.ops.norm import feature_l2_norm  # noqa: E402

B = int(sys.argv[1]) if len(sys.argv) > 1 else 8
DT = jnp.bfloat16 if (len(sys.argv) > 2 and sys.argv[2] == "bf16") else jnp.float32
S, C = 25, 1024


def init_params(key):
    ks = jax.random.split(key, 3)
    chans = [(1, 16), (16, 16), (16, 1)]
    return [
        dict(zip(("w", "b"), conv4d_init(k, 5, ci, co)))
        for k, (ci, co) in zip(ks, chans)
    ]


def stack_loss(params, corr):
    params = jax.tree.map(lambda x: x.astype(DT), params)
    out = neigh_consensus(params, corr, symmetric=True)
    return jnp.mean(out.astype(jnp.float32))


def main():
    params0 = init_params(jax.random.key(7))

    for variant in ("nc_fwd", "nc_dx", "nc_dw", "nc_both"):

        def tick(carry, _v=variant):
            fa, fb, params = carry
            corr = correlation_4d(fa, fb).astype(DT)
            if _v == "nc_fwd":
                val = stack_loss(params, corr)
                gp, gc = None, None
            elif _v == "nc_dx":
                val, gc = jax.value_and_grad(
                    lambda c: stack_loss(jax.lax.stop_gradient(params), c)
                )(corr)
                gp = None
            elif _v == "nc_dw":
                val, gp = jax.value_and_grad(stack_loss)(params, corr)
                gc = None
            else:
                val, (gp, gc) = jax.value_and_grad(stack_loss, argnums=(0, 1))(
                    params, corr)
            fa = fa + (val * 1e-9).astype(fa.dtype)
            if gc is not None:
                fa = fa + (jnp.sum(gc.astype(jnp.float32)) * 1e-12).astype(fa.dtype)
            if gp is not None:
                params = jax.tree.map(
                    lambda p, gg: p + (jnp.sum(gg.astype(jnp.float32)) * 1e-12
                                       ).astype(p.dtype), params, gp)
            return (fa, fb, params)

        def make_input(key):
            k1, k2 = jax.random.split(key)
            fa = feature_l2_norm(jax.random.normal(k1, (B, S, S, C), jnp.float32))
            fb = feature_l2_norm(jax.random.normal(k2, (B, S, S, C), jnp.float32))
            return (fa, fb, params0)

        try:
            ms = timeit(tick, make_input, n_long=4, reps=3)
            print(f"{variant:8s} {ms:8.1f} ms/step  {ms / B:6.2f} ms/pair",
                  flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"{variant:8s} FAILED: {str(e)[:200]}", flush=True)


if __name__ == "__main__":
    main()


# The plain-AD vs custom-VJP composed comparison lives in
# tools/vjp_sweep_probe.py (its 'plain' and 'custom_def' rows).
