#!/usr/bin/env python
"""Fail if any ncnet_tpu LIBRARY module calls bare ``print()``.

The observability layer (``ncnet_tpu/observability/logging.py``) is the one
console sink: library code must log through ``get_logger(...)`` so every
rendered line is also teed into the structured event log.  A bare
``print()`` silently reopens the side channel the PR 5 migration closed —
this checker (run as a tier-1 test, ``tests/test_observability.py``) locks
the migration in.

Exemptions:
  * ``ncnet_tpu/cli/`` — CLI entry points ARE the console; their banner /
    result prints are user interface, not run telemetry;
  * docstrings/comments — the scan is AST-based, so ``print()`` mentioned
    in prose never trips it;
  * ``sys.stdout.write`` in the logger itself (that is the sink).

Usage: ``python tools/check_no_bare_print.py [package_dir ...]`` — prints
one ``path:line`` per violation and exits 1 if any were found.  Several
targets may be given (each walked independently; a ``.py`` file is checked
directly), so the tier-1 test pins the round-9 additions —
``observability/tracing.py``, ``observability/perfstore.py``,
``ops/tier_cache.py``, ``utils/compat.py`` — explicitly alongside the
whole-package walk.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

EXEMPT_DIRS = ("cli",)


def _check_file(path: str, hits: List[Tuple[str, int]]) -> None:
    with open(path, "r") as f:
        try:
            tree = ast.parse(f.read(), path)
        except SyntaxError as e:  # a broken module is its own bug
            hits.append((path, e.lineno or 0))
            return
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"):
            hits.append((path, node.lineno))


def find_bare_prints(target: str) -> List[Tuple[str, int]]:
    """``(path, lineno)`` for every ``print(...)`` call in a non-exempt
    module under directory ``target`` (or in the single file ``target``).
    AST-based: docstrings, comments and attribute calls like
    ``pprint.print`` do not count."""
    hits: List[Tuple[str, int]] = []
    if os.path.isfile(target):
        _check_file(target, hits)
        return hits
    for root, dirs, files in os.walk(target):
        rel = os.path.relpath(root, target)
        parts = [] if rel == "." else rel.split(os.sep)
        if any(p in EXEMPT_DIRS for p in parts):
            continue
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            _check_file(os.path.join(root, fname), hits)
    return hits


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    targets = args or [os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "ncnet_tpu",
    )]
    hits: List[Tuple[str, int]] = []
    for target in targets:
        hits.extend(find_bare_prints(target))
    for path, lineno in hits:
        print(f"{path}:{lineno}: bare print() in a library module "
              "(use ncnet_tpu.observability.get_logger)")
    if hits:
        print(f"{len(hits)} bare print call(s) found under "
              f"{', '.join(targets)} (exempt: {', '.join(EXEMPT_DIRS)}/)")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
