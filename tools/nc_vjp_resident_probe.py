#!/usr/bin/env python
"""Parity + timing of the RESIDENT Pallas NC backward (r7) on real hardware.

Usage: python tools/nc_vjp_resident_probe.py [batch_volumes] [arch]
  arch: 'pf' (default: 25⁴, k=5, (16,16,1)) or 'ivd' (25⁴, k=3, (16,1))

Run on a TPU backend — this is the measurement companion of
ops/nc_fused_lane_vjp.py, built so the next TPU-attached session can record:

  * whether the per-stage compile probes are green at the flagship shape
    (stage 1 accounts to ~15.7 MiB of VMEM — three 16-channel structures
    resident at once — right at the v5e ceiling; if Mosaic rejects it the
    chooser falls back to the XLA backward and THAT is the finding);
  * the composed grad-step wall of the fused VJP vs the XLA autodiff
    backward, plus each backward stage's isolated wall (the dX/dW split).

Prior-probe findings folded in (what this kernel set replaces):

  * tools/vjp_probe.py (r4, v5e, 25⁴ symmetric stack, fp32 bs8):
    plain XLA AD 48.4 ms/pair / 12.7 GB temp; conv4d's custom dw-variant
    VJP 56.9 ms/pair / 7.2 GB — every XLA-level dw reformulation was a
    SPEED LOSS (dw_unroll blew memory to 20.9 GB via channel-minor
    relayouts); the backward needed its own kernel, not another XLA
    formulation.
  * tools/nc_grad_split_probe.py (same rig): the backward splits roughly
    2:1 dW-chain : dX-chain on top of a 1× forward — recompute-in-kernel
    plus true dX/dW kernels is the ~3×-forward budget this module targets
    (a pos+neg step ≈ 6 filter-forward-equivalents).
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")

from _timing import timeit  # noqa: E402

B = int(sys.argv[1]) if len(sys.argv) > 1 else 8
ARCH = sys.argv[2] if len(sys.argv) > 2 else "pf"
S = 25
KS, CHS = ((5, 5, 5), (16, 16, 1)) if ARCH == "pf" else ((3, 3), (16, 1))
DT = jnp.bfloat16


def make_params(key):
    params, c_in = [], 1
    for k, c_out in zip(KS, CHS):
        k1, k2, key = jax.random.split(key, 3)
        params.append({
            "w": jax.random.normal(k1, (k,) * 4 + (c_in, c_out), DT) * 0.05,
            "b": jax.random.normal(k2, (c_out,), DT) * 0.1,
        })
        c_in = c_out
    return params


def xla_stack(params, x):
    from ncnet_tpu.ops.conv4d import conv4d

    for layer in params:
        x = jax.nn.relu(conv4d(x, layer["w"], layer["b"]))
    return x


def main():
    from ncnet_tpu.ops.nc_fused_lane import fused_layout_in
    from ncnet_tpu.ops.nc_fused_lane_vjp import (
        _vjp_stage,
        _vjp_stage_je,
        _vjp_stage_vmem_bytes,
        choose_fused_vjp,
        cotangent_layout_in,
        fused_vjp_compiles,
        fused_vjp_feasible,
        nc_stack_fused_vjp,
    )
    from ncnet_tpu.ops.nc_fused_lane import nc_stack_fused

    print(f"device={jax.devices()[0].device_kind} n_volumes={B} arch={ARCH}")
    shape_args = (S, S, S, S, KS, CHS)
    print("feasible:", fused_vjp_feasible(*shape_args))
    for l in range(len(KS)):
        je = _vjp_stage_je(l, *shape_args)
        mb = _vjp_stage_vmem_bytes(l, S, S, S, KS, CHS, max(je, 1)) / 2 ** 20
        print(f"  stage {l}: je={je}  vmem≈{mb:.2f} MiB")
    print("compiles:", fused_vjp_compiles(*shape_args))
    print("chooser :", choose_fused_vjp(*shape_args))

    key = jax.random.key(0)
    params = make_params(key)
    x = jax.random.normal(jax.random.key(9), (2, S, S, S, S, 1), DT) * 0.1
    out, vjp_ref = jax.vjp(xla_stack, params, x)
    g = jax.random.normal(jax.random.key(3), out.shape, DT) * 0.1
    dp_ref, dx_ref = vjp_ref(g)
    dp, dx = jax.jit(nc_stack_fused_vjp)(params, x, g)
    worst = 0.0
    for a, b in zip(jax.tree.leaves((dp, dx)), jax.tree.leaves((dp_ref, dx_ref))):
        a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
        worst = max(worst, float(np.max(np.abs(a - b)))
                    / max(1e-6, float(np.max(np.abs(b)))))
    # boundary-cell mask flips (tests/test_nc_vjp.py module docstring)
    # inflate this on random data; the margin-built test suite is the
    # parity authority — this prints the raw field number
    print(f"parity vs XLA AD (raw random data): worst rel {worst:.3%}")

    # compiled-program memory ledger rows (observability/memory.py): the
    # MEASURED memory_analysis of the fused backward vs the XLA-AD twin,
    # persisted beside the tier cache and emitted as memory_ledger events —
    # so the next real-TPU session answers the ~15.7 MiB stage-1 VMEM
    # question with measured, not accounted, numbers
    from ncnet_tpu.observability import memory as obs_memory

    sig = (f"{S}x{S}x{S}x{S}|k={','.join(str(k) for k in KS)}"
           f"|c={','.join(str(c) for c in CHS)}")
    try:
        compiled = jax.jit(nc_stack_fused_vjp).lower(params, x, g).compile()
        row = obs_memory.record_program(
            "nc_vjp_resident_probe", sig, analysis=compiled,
            tier="resident_vjp", source="probe")
        print(f"ledger fused vjp: {row}")

        def xla_ad(params, x, g):
            _, vjp = jax.vjp(xla_stack, params, x)
            return vjp(g)

        compiled = jax.jit(xla_ad).lower(params, x, g).compile()
        row = obs_memory.record_program(
            "nc_vjp_xla_ad", sig, analysis=compiled, tier="xla",
            source="probe")
        print(f"ledger xla ad   : {row}")
    except Exception as e:  # noqa: BLE001 — the ledger must not kill timing
        print(f"ledger: FAILED {str(e)[:160]}")

    def make_input(key):
        k1, k2, kk = jax.random.split(key, 3)
        return (
            jax.random.normal(k1, (B, S, S, S, S, 1), DT) * 0.1,
            jax.random.normal(k2, (B, S, S, S, S, CHS[-1]), DT) * 0.1,
            make_params(kk),
        )

    def consume(carry, dp, dx):
        x, g, params = carry
        eps = sum(jnp.sum(leaf.astype(jnp.float32))
                  for l_ in dp for leaf in l_.values())
        x = x + (eps * 1e-12).astype(x.dtype) + dx.astype(x.dtype) * 1e-12
        return (x, g, params)

    def fused_grad_step(carry):
        x, g, params = carry
        _, vjp = jax.vjp(nc_stack_fused, params, x)
        dp, dx = vjp(g)
        return consume(carry, dp, dx)

    def fused_direct_step(carry):
        x, g, params = carry
        dp, dx = nc_stack_fused_vjp(params, x, g)
        return consume(carry, dp, dx)

    def xla_grad_step(carry):
        x, g, params = carry
        _, vjp = jax.vjp(xla_stack, params, x)
        dp, dx = vjp(g)
        return consume(carry, dp, dx)

    ms_f = timeit(fused_grad_step, make_input, per=B, n_long=6)
    ms_d = timeit(fused_direct_step, make_input, per=B, n_long=6)
    ms_x = timeit(xla_grad_step, make_input, per=B, n_long=6)
    print(f"fused fwd+bwd (custom vjp): {ms_f:7.3f} ms/volume")
    print(f"fused bwd alone           : {ms_d:7.3f} ms/volume")
    print(f"xla   fwd+bwd (autodiff)  : {ms_x:7.3f} ms/volume")

    # per-stage walls (the dX/dW attribution): time each backward stage in
    # isolation on staged layouts
    k = KS[0]
    for l in reversed(range(len(KS))):
        co_l = CHS[l]

        def stage_step(carry, l=l, co_l=co_l):
            x, g, params = carry
            xp = fused_layout_in(x, k)
            gamma = cotangent_layout_in(
                jnp.broadcast_to(g[..., :1], x.shape[:-1] + (co_l,))
                if g.shape[-1] != co_l else g, k)
            gam, dw2, dbp = _vjp_stage(
                l, params, xp, gamma, ha=S, wa=S, hb=S, wb=S,
                interpret=False)
            eps = (jnp.sum(dw2) + jnp.sum(dbp)
                   + jnp.sum(gam.astype(jnp.float32))) * 1e-12
            return (x + eps.astype(x.dtype), g, params)

        try:
            t = timeit(stage_step, make_input, per=B, n_long=6)
            print(f"  stage {l} (gz+dW+db+Γ): {t:7.3f} ms/volume")
        except Exception as e:  # noqa: BLE001
            print(f"  stage {l}: FAILED {str(e)[:160]}")


if __name__ == "__main__":
    main()
