#!/usr/bin/env python
"""Reference implementation of the heartbeat watchdog contract.

The training loop bumps ``<telemetry_dir>/heartbeat.json`` atomically every
step (``ncnet_tpu/observability/device.py::Heartbeat``); the documented
contract for external watchdogs is *mtime age > a few step walls ⇒ the
process is stalled or dead*.  This tool is that watchdog: one invocation
judges liveness NOW (cron / a supervisor loop / a CI babysitter runs it
periodically), with the stall threshold derived from the run's own cadence
rather than a guessed constant:

  * the recent median step wall comes from the sibling event log's last
    ``step`` events (default: ``events.jsonl`` beside the heartbeat file) —
    a run stepping at 30 s/step gets a proportionally longer leash than one
    at 0.3 s/step;
  * stalled ⇔ heartbeat mtime age > ``N × median`` (default N=10), floored
    at ``--min-age`` seconds (default 60) so startup jitter, checkpoint
    pauses, or a watchdog racing the very first beat cannot false-positive;
  * no event log / no step events ⇒ the threshold degrades to ``--min-age``
    alone, and the tool says so.

Exit codes: 0 = alive, 3 = STALLED, 2 = no heartbeat file (not started, or
already cleaned up — distinct so supervisors can treat it differently).

Usage::

    python tools/stall_watchdog.py <telemetry_dir>/heartbeat.json
        [--events <events.jsonl>] [--factor 10] [--min-age 60] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
from typing import Any, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ncnet_tpu.observability.device import Heartbeat  # noqa: E402
from ncnet_tpu.observability.events import replay_events  # noqa: E402


def recent_median_step_wall(events_path: str,
                            tail: int = 32) -> Optional[float]:
    """Median ``wall_s`` of the last ``tail`` cadence events, or None when
    the log is missing/unreadable/cadence-less (the caller falls back to
    the static floor).  Cadence events: training ``step``s, and serving
    ``serve_batch``es (the match service beats its heartbeat once per
    dispatched batch, so the batch wall IS its step wall — one watchdog
    contract for both process shapes).  Torn tails are tolerated by
    ``replay_events``."""
    try:
        _, events = replay_events(events_path)
    except (OSError, ValueError):
        return None
    walls: List[float] = [
        e["wall_s"] for e in events
        if e.get("event") in ("step", "serve_batch")
        and isinstance(e.get("wall_s"), (int, float)) and e["wall_s"] > 0
    ][-tail:]
    if not walls:
        return None
    return float(statistics.median(walls))


def replica_batch_cadence(events_path: str,
                          tail: int = 256) -> Dict[str, Dict[str, Any]]:
    """Per-replica ``serve_batch`` cadence from a replica-pool service's
    event log: ``{replica_id: {"last_t", "median_wall_s", "n"}}`` (empty
    when the log has no replica-tagged batches — a training run, or a
    pre-pool serving log).  ``last_t`` is the wall-clock timestamp of the
    replica's most recent completed batch."""
    try:
        _, events = replay_events(events_path)
    except (OSError, ValueError):
        return {}
    per: Dict[str, Dict[str, Any]] = {}
    for e in events:
        if e.get("event") != "serve_batch" or e.get("replica") is None:
            continue
        d = per.setdefault(str(e["replica"]), {"walls": [], "last_t": None})
        if isinstance(e.get("wall_s"), (int, float)) and e["wall_s"] > 0:
            d["walls"].append(e["wall_s"])
        if isinstance(e.get("t"), (int, float)):
            d["last_t"] = e["t"]
    out: Dict[str, Dict[str, Any]] = {}
    for rid, d in per.items():
        walls = d["walls"][-tail:]
        out[rid] = {
            "last_t": d["last_t"],
            "median_wall_s": (float(statistics.median(walls))
                              if walls else None),
            "n": len(walls),
        }
    return out


def judge(heartbeat_path: str, events_path: Optional[str] = None,
          factor: float = 10.0, min_age: float = 60.0) -> Dict[str, Any]:
    """One liveness verdict: ``{"status": "alive"|"stalled"|"missing", ...}``
    with the evidence (age, threshold, median step wall, last payload).

    Replica-pool awareness: the service is alive if ANY replica shows
    recent batch cadence — one wedged replica (whose lane stops emitting
    ``serve_batch``) must not flag a healthy pool as STALLED.  Normally the
    pool-wide heartbeat (bumped per dispatched batch on any replica)
    already says so; the per-replica check is the backstop when the
    heartbeat file is stale or unwritable but the event log shows a lane
    still draining, and the ``replicas`` breakdown in the verdict names
    which lanes are fresh vs wedged either way."""
    age = Heartbeat.age_s(heartbeat_path)
    if age is None:
        return {"status": "missing", "heartbeat": heartbeat_path}
    if events_path is None:
        events_path = os.path.join(
            os.path.dirname(os.path.abspath(heartbeat_path)), "events.jsonl")
    median = recent_median_step_wall(events_path)
    threshold = max(min_age, factor * median) if median else min_age
    status = "stalled" if age > threshold else "alive"
    # per-replica cadence: the breakdown always ships; a recent lane also
    # overrides a stale heartbeat
    cadence = replica_batch_cadence(events_path)
    replicas: Dict[str, Any] = {}
    alive_via = None
    now = time.time()
    for rid, c in sorted(cadence.items()):
        rep_threshold = max(min_age, factor * c["median_wall_s"]) \
            if c["median_wall_s"] else min_age
        rep_age = (now - c["last_t"]) if c["last_t"] else None
        recent = rep_age is not None and rep_age <= rep_threshold
        replicas[rid] = {
            "last_batch_age_s": round(rep_age, 3) if rep_age is not None
            else None,
            "median_wall_s": (round(c["median_wall_s"], 6)
                              if c["median_wall_s"] else None),
            "threshold_s": round(rep_threshold, 3),
            "n": c["n"],
            "recent": recent,
        }
        if status == "stalled" and recent and alive_via is None:
            alive_via = f"replica_cadence:{rid}"
            status = "alive"
    verdict: Dict[str, Any] = {
        "status": status,
        "heartbeat": heartbeat_path,
        "age_s": round(age, 3),
        "threshold_s": round(threshold, 3),
        "median_step_wall_s": round(median, 6) if median else None,
        "factor": factor,
        "min_age_s": min_age,
        "events": events_path if (median or replicas) else None,
    }
    if replicas:
        verdict["replicas"] = replicas
    if alive_via:
        verdict["alive_via"] = alive_via
    payload = Heartbeat.read(heartbeat_path)
    if payload:
        verdict["last_beat"] = payload
    return verdict


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Judge a training run's liveness from its heartbeat "
                    "file + event log")
    ap.add_argument("heartbeat", help="path to heartbeat.json")
    ap.add_argument("--events", default=None,
                    help="event log for the step-wall cadence (default: "
                         "events.jsonl beside the heartbeat file)")
    ap.add_argument("--factor", type=float, default=10.0,
                    help="stall threshold = factor x median step wall "
                         "(default 10)")
    ap.add_argument("--min-age", type=float, default=60.0,
                    help="threshold floor in seconds (default 60; also the "
                         "whole threshold when no step cadence is readable)")
    ap.add_argument("--json", action="store_true",
                    help="emit the verdict as one JSON document")
    args = ap.parse_args(argv)

    verdict = judge(args.heartbeat, events_path=args.events,
                    factor=args.factor, min_age=args.min_age)
    if args.json:
        print(json.dumps(verdict, indent=2, sort_keys=True))
    elif verdict["status"] == "missing":
        print(f"no heartbeat at {verdict['heartbeat']} (run not started, "
              "telemetry off, or already cleaned up)")
    else:
        cadence = (f"median step wall {verdict['median_step_wall_s']}s "
                   f"x {verdict['factor']}"
                   if verdict["median_step_wall_s"]
                   else f"no step cadence; floor {verdict['min_age_s']}s")
        beat = verdict.get("last_beat") or {}
        via = (f" [alive via {verdict['alive_via']}]"
               if verdict.get("alive_via") else "")
        print(f"{verdict['status'].upper()}{via}: heartbeat age "
              f"{verdict['age_s']}s vs threshold {verdict['threshold_s']}s "
              f"({cadence}); last beat: step {beat.get('step')}, "
              f"pid {beat.get('pid')}, run {beat.get('run')}")
        for rid, r in (verdict.get("replicas") or {}).items():
            tag = "fresh" if r["recent"] else "wedged/idle"
            print(f"  replica {rid}: last batch "
                  f"{r['last_batch_age_s']}s ago vs {r['threshold_s']}s "
                  f"({tag}; n={r['n']})")
    return {"alive": 0, "missing": 2, "stalled": 3}[verdict["status"]]


if __name__ == "__main__":
    raise SystemExit(main())
