#!/usr/bin/env python
"""Reference implementation of the heartbeat watchdog contract.

The training loop bumps ``<telemetry_dir>/heartbeat.json`` atomically every
step (``ncnet_tpu/observability/device.py::Heartbeat``); the documented
contract for external watchdogs is *mtime age > a few step walls ⇒ the
process is stalled or dead*.  This tool is that watchdog: one invocation
judges liveness NOW (cron / a supervisor loop / a CI babysitter runs it
periodically), with the stall threshold derived from the run's own cadence
rather than a guessed constant:

  * the recent median step wall comes from the sibling event log's last
    ``step`` events (default: ``events.jsonl`` beside the heartbeat file) —
    a run stepping at 30 s/step gets a proportionally longer leash than one
    at 0.3 s/step;
  * stalled ⇔ heartbeat mtime age > ``N × median`` (default N=10), floored
    at ``--min-age`` seconds (default 60) so startup jitter, checkpoint
    pauses, or a watchdog racing the very first beat cannot false-positive;
  * no event log / no step events ⇒ the threshold degrades to ``--min-age``
    alone, and the tool says so.

Exit codes: 0 = alive, 3 = STALLED, 2 = no heartbeat file (not started, or
already cleaned up — distinct so supervisors can treat it differently).

**Remote mode (``--url``)**: a serving process with the introspection plane
(``ncnet_tpu/serving/introspect.py``) exports the same liveness signal over
HTTP — ``/healthz``'s ``activity.age_s`` is seconds since the pool last
dispatched a batch or deliberately idled, exactly the heartbeat-mtime
semantics.  ``--url http://host:port`` polls that instead of a local file,
so the watchdog runs from ANOTHER host (the multi-host deployment shape)
with no shared filesystem.  The event-log cadence backstop keeps its PR 10
semantics in BOTH modes: when ``--events`` is readable, the stall
threshold derives from the run's own batch cadence and a recent
per-replica lane overrides a stale primary signal (one wedged replica
cannot flag a healthy pool STALLED); without a readable log the threshold
degrades to ``--min-age`` alone.  An unreachable endpoint maps to the
``missing`` verdict (exit 2) — "not started or already gone", the same
supervisor semantics as a missing heartbeat file.

A document carrying a ``model_version`` / ``rollout`` section
(ncnet_tpu/serving/rollout.py) ships a model advisory: a pod mid-rollout
is intentionally mixed-version with one replica drained at a time, so the
verdict names the phase and the version split instead of letting either
read as trouble — and, like the store advisory, never flags a stall.

A health document carrying a feature-store section (ncnet_tpu/store/)
ships a store advisory in the verdict: a DEGRADED store fails OPEN (every
query still answered, via recompute), so store-DEGRADED is rendered as a
warning about the disk and NEVER flags the process STALLED.

``--url`` also judges a multi-host **router** (``serving/router.py``): the
primary signal is the router document's aggregate ``activity.age_s``
(advances when ANY backend settles a result), and the document's
per-backend rows (``last_result_age_s`` vs a threshold from each
backend's own wall EWMA) ship as a staleness breakdown — a recent backend
overrides a stale aggregate, the router-tier mirror of the per-replica
backstop, with no shared filesystem needed.

**Pod mode (repeated ``--url``)**: one watchdog invocation judges every
process of a pod — pass ``--url`` once per host (router, backends,
retrieval coordinator) and the tool renders ONE staleness table, one row
per target, each judged by its own document exactly as in single-URL
mode.  The exit status is the WORST verdict across the pod (stalled=3 >
missing=2 > alive=0), so a supervisor watching the whole deployment
needs exactly one cron line.

Usage::

    python tools/stall_watchdog.py <telemetry_dir>/heartbeat.json
        [--events <events.jsonl>] [--factor 10] [--min-age 60] [--json]
    python tools/stall_watchdog.py --url http://host:8080
        [--events <events.jsonl>] [--factor 10] [--min-age 60] [--json]
    python tools/stall_watchdog.py --url http://router:8080 \
        --url http://backend1:8081 --url http://backend2:8082 [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
from typing import Any, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ncnet_tpu.observability.device import Heartbeat  # noqa: E402
from ncnet_tpu.observability.events import replay_events  # noqa: E402


def recent_median_step_wall(events_path: str,
                            tail: int = 32) -> Optional[float]:
    """Median ``wall_s`` of the last ``tail`` cadence events, or None when
    the log is missing/unreadable/cadence-less (the caller falls back to
    the static floor).  Cadence events: training ``step``s, and serving
    ``serve_batch``es (the match service beats its heartbeat once per
    dispatched batch, so the batch wall IS its step wall — one watchdog
    contract for both process shapes).  Torn tails are tolerated by
    ``replay_events``."""
    try:
        _, events = replay_events(events_path)
    except (OSError, ValueError):
        return None
    walls: List[float] = [
        e["wall_s"] for e in events
        if e.get("event") in ("step", "serve_batch")
        and isinstance(e.get("wall_s"), (int, float)) and e["wall_s"] > 0
    ][-tail:]
    if not walls:
        return None
    return float(statistics.median(walls))


def replica_batch_cadence(events_path: str,
                          tail: int = 256) -> Dict[str, Dict[str, Any]]:
    """Per-replica ``serve_batch`` cadence from a replica-pool service's
    event log: ``{replica_id: {"last_t", "median_wall_s", "n"}}`` (empty
    when the log has no replica-tagged batches — a training run, or a
    pre-pool serving log).  ``last_t`` is the wall-clock timestamp of the
    replica's most recent completed batch."""
    try:
        _, events = replay_events(events_path)
    except (OSError, ValueError):
        return {}
    per: Dict[str, Dict[str, Any]] = {}
    for e in events:
        if e.get("event") != "serve_batch" or e.get("replica") is None:
            continue
        d = per.setdefault(str(e["replica"]), {"walls": [], "last_t": None})
        if isinstance(e.get("wall_s"), (int, float)) and e["wall_s"] > 0:
            d["walls"].append(e["wall_s"])
        if isinstance(e.get("t"), (int, float)):
            d["last_t"] = e["t"]
    out: Dict[str, Dict[str, Any]] = {}
    for rid, d in per.items():
        walls = d["walls"][-tail:]
        out[rid] = {
            "last_t": d["last_t"],
            "median_wall_s": (float(statistics.median(walls))
                              if walls else None),
            "n": len(walls),
        }
    return out


def _apply_replica_backstop(verdict: Dict[str, Any], events_path: str,
                            factor: float, min_age: float) -> None:
    """The PR 10 backstop, shared by both modes: per-replica ``serve_batch``
    cadence always ships in the verdict, and a recent lane overrides a
    stale primary signal (heartbeat mtime or HTTP activity age) — one
    wedged replica must not flag a healthy pool STALLED."""
    cadence = replica_batch_cadence(events_path)
    replicas: Dict[str, Any] = {}
    alive_via = None
    now = time.time()
    for rid, c in sorted(cadence.items()):
        rep_threshold = max(min_age, factor * c["median_wall_s"]) \
            if c["median_wall_s"] else min_age
        rep_age = (now - c["last_t"]) if c["last_t"] else None
        recent = rep_age is not None and rep_age <= rep_threshold
        replicas[rid] = {
            "last_batch_age_s": round(rep_age, 3) if rep_age is not None
            else None,
            "median_wall_s": (round(c["median_wall_s"], 6)
                              if c["median_wall_s"] else None),
            "threshold_s": round(rep_threshold, 3),
            "n": c["n"],
            "recent": recent,
        }
        if verdict["status"] == "stalled" and recent and alive_via is None:
            alive_via = f"replica_cadence:{rid}"
            verdict["status"] = "alive"
    if replicas:
        verdict["replicas"] = replicas
    if alive_via:
        verdict["alive_via"] = alive_via


def _apply_backend_backstop(verdict: Dict[str, Any], doc: Dict[str, Any],
                            factor: float, min_age: float) -> None:
    """The router-tier backstop (mirror of the PR 10 per-replica one, but
    sourced from the ``/healthz`` document itself — cross-host, no shared
    filesystem needed): a router document carries per-backend rows with
    ``last_result_age_s`` and the backend's wall EWMA, so the verdict
    ships a per-backend staleness breakdown and a RECENT backend overrides
    a stale aggregate activity stamp — one wedged host cannot flag a
    healthy pod STALLED."""
    rows = (doc.get("pod") or {}).get("backends")
    if not isinstance(rows, list) or not rows:
        return
    backends: Dict[str, Any] = {}
    alive_via = None
    for row in rows:
        if not isinstance(row, dict) or row.get("id") is None:
            continue
        ewma_ms = row.get("ewma_wall_ms")
        threshold = max(min_age, factor * ewma_ms / 1e3) \
            if isinstance(ewma_ms, (int, float)) and ewma_ms > 0 else min_age
        age = row.get("last_result_age_s")
        recent = isinstance(age, (int, float)) and age <= threshold
        backends[str(row["id"])] = {
            "state": row.get("state"),
            "last_result_age_s": age if isinstance(age, (int, float))
            else None,
            "threshold_s": round(threshold, 3),
            "recent": recent,
        }
        if verdict["status"] == "stalled" and recent and alive_via is None:
            alive_via = f"backend_cadence:{row['id']}"
            verdict["status"] = "alive"
    if backends:
        verdict["backends"] = backends
    if alive_via:
        verdict["alive_via"] = alive_via


def _apply_store_advisory(verdict: Dict[str, Any],
                          doc: Dict[str, Any]) -> None:
    """Feature-store advisory from the health document's ``store`` section
    (ncnet_tpu/store/): a DEGRADED store FAILS OPEN — every query is still
    answered via recompute — so degraded-but-serving is an operator
    warning about the DISK, never a stall.  This helper surfaces the state
    in the verdict and deliberately never touches the liveness status."""
    st = doc.get("store")
    if not isinstance(st, dict):
        return
    c = st.get("counters") or {}
    verdict["store"] = {
        "state": st.get("state"),
        "reason": st.get("reason"),
        "hit_pct": st.get("hit_pct"),
        "corrupt": c.get("corrupt", 0),
        "degraded_ops": c.get("degraded_ops", 0),
    }


def _apply_retrieval_advisory(verdict: Dict[str, Any],
                              doc: Dict[str, Any]) -> None:
    """Retrieval-tier advisory (ncnet_tpu/retrieval/): a coordinator's
    document carries shard capacity and the served coverage distribution.
    Like the store advisory this never touches liveness — a DEGRADED
    coordinator is still answering every query with an honest ``coverage``
    field; what the operator needs surfaced is HOW MUCH of the database
    those answers consulted, and which shards the per-row breakdown
    (``verdict["backends"]``, the shard rows here) says are dead."""
    if doc.get("role") != "retrieval":
        return
    r = doc.get("retrieval") or {}
    pod = doc.get("pod") or {}
    verdict["retrieval"] = {
        "shards_ready": pod.get("ready"),
        "shards_total": pod.get("total"),
        "replication": r.get("replication"),
        "coverage_p50": r.get("coverage_p50"),
        "coverage_min": r.get("coverage_min"),
        "min_coverage": r.get("min_coverage"),
    }


def _apply_rollout_advisory(verdict: Dict[str, Any],
                            doc: Dict[str, Any]) -> None:
    """Model-version / live-rollout advisory (PR 18,
    ncnet_tpu/serving/rollout.py).  A pod mid-rollout is INTENTIONALLY
    mixed-version — one canary or rolling-swap replica on the candidate
    while the rest serve the incumbent — so this surfaces the phase and
    the version split instead of letting an operator read the drained
    replica or the version skew as trouble.  Strictly an advisory: a
    rollout never touches the liveness status (the whole design point is
    that the pod keeps serving through it)."""
    out: Dict[str, Any] = {}
    if doc.get("model_version"):
        out["model_version"] = doc["model_version"]
    ro = doc.get("rollout")
    if isinstance(ro, dict) and ro.get("phase") not in (None, "IDLE"):
        out["rollout"] = {
            "phase": ro.get("phase"),
            "old_version": ro.get("old_version"),
            "new_version": ro.get("new_version"),
            "reason": ro.get("reason"),
        }
    # per-replica version split (service doc) / per-pod version list
    # (router doc): more than one distinct version = mixed-version window
    versions: List[str] = []
    for row in (doc.get("pool") or {}).get("replicas") or []:
        if isinstance(row, dict) and row.get("model_version"):
            versions.append(str(row["model_version"]))
    pod_versions = (doc.get("pod") or {}).get("model_versions")
    if isinstance(pod_versions, list):
        versions.extend(str(v) for v in pod_versions)
    distinct = sorted(set(versions))
    if len(distinct) > 1:
        out["mixed_versions"] = distinct
    if out:
        verdict["model"] = out


def _apply_hbm_warning(verdict: Dict[str, Any], doc: Dict[str, Any],
                       warn_pct: float) -> None:
    """HBM-pressure advisory from the health document's memory section
    (observability/memory.py): any replica whose fill exceeds
    ``warn_pct`` lands in ``verdict["hbm_warning"]``.  Strictly a WARNING —
    pressure is not a stall, so the liveness status never changes here."""
    hbm = ((doc.get("memory") or {}).get("hbm")) or {}
    hot = {}
    for rid, s in sorted(hbm.items()):
        fill = s.get("fill_pct")
        if isinstance(fill, (int, float)) and fill >= warn_pct:
            hot[str(rid)] = {
                "fill_pct": fill,
                "bytes_in_use": s.get("bytes_in_use"),
                "bytes_limit": s.get("bytes_limit"),
            }
    if hot:
        verdict["hbm_warning"] = {"threshold_pct": warn_pct,
                                  "replicas": hot}


def judge_url(url: str, events_path: Optional[str] = None,
              factor: float = 10.0, min_age: float = 60.0,
              timeout: float = 5.0,
              hbm_warn_pct: float = 90.0) -> Dict[str, Any]:
    """Remote liveness verdict over the introspection plane: the primary
    signal is ``/healthz``'s ``activity.age_s`` (seconds since the pool
    last dispatched or deliberately idled — the HTTP twin of the heartbeat
    mtime), thresholded by the event-log cadence when one is readable.
    Judges a ``MatchRouter``'s document the same way (its aggregate
    activity stamp advances on any backend's result), plus a per-backend
    staleness breakdown read from the document's backend rows — the
    cross-host mirror of the per-replica backstop, so one wedged host
    cannot flag a healthy pod STALLED.  Unreachable ⇒ ``missing``
    (exit 2), same as a missing heartbeat file."""
    import json as _json
    import urllib.error
    import urllib.request

    base = url.rstrip("/")
    if not base.endswith("/healthz"):
        base += "/healthz"
    try:
        try:
            with urllib.request.urlopen(base, timeout=timeout) as r:
                doc = _json.loads(r.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            if e.code != 503:
                raise
            # 503 is a DRAINING/STOPPED service answering honestly — the
            # plane is alive even though probes should stop routing
            doc = _json.loads(e.read().decode("utf-8"))
    except Exception as e:  # noqa: BLE001 — any transport failure is the
        # same verdict: nothing is answering there
        return {"status": "missing", "url": base,
                "error": f"{type(e).__name__}: {e}"}
    age = (doc.get("activity") or {}).get("age_s")
    if not isinstance(age, (int, float)):
        return {"status": "missing", "url": base,
                "error": "healthz document has no activity.age_s"}
    median = recent_median_step_wall(events_path) if events_path else None
    threshold = max(min_age, factor * median) if median else min_age
    verdict: Dict[str, Any] = {
        "status": "stalled" if age > threshold else "alive",
        "mode": "url",
        "url": base,
        "state": doc.get("state"),
        "role": doc.get("role", "service"),
        "age_s": round(float(age), 3),
        "threshold_s": round(threshold, 3),
        "median_step_wall_s": round(median, 6) if median else None,
        "factor": factor,
        "min_age_s": min_age,
        "events": events_path if median else None,
    }
    if events_path:
        _apply_replica_backstop(verdict, events_path, factor, min_age)
    _apply_backend_backstop(verdict, doc, factor, min_age)
    _apply_rollout_advisory(verdict, doc)
    _apply_retrieval_advisory(verdict, doc)
    _apply_hbm_warning(verdict, doc, hbm_warn_pct)
    _apply_store_advisory(verdict, doc)
    return verdict


def judge(heartbeat_path: str, events_path: Optional[str] = None,
          factor: float = 10.0, min_age: float = 60.0) -> Dict[str, Any]:
    """One liveness verdict: ``{"status": "alive"|"stalled"|"missing", ...}``
    with the evidence (age, threshold, median step wall, last payload).

    Replica-pool awareness: the service is alive if ANY replica shows
    recent batch cadence — one wedged replica (whose lane stops emitting
    ``serve_batch``) must not flag a healthy pool as STALLED.  Normally the
    pool-wide heartbeat (bumped per dispatched batch on any replica)
    already says so; the per-replica check is the backstop when the
    heartbeat file is stale or unwritable but the event log shows a lane
    still draining, and the ``replicas`` breakdown in the verdict names
    which lanes are fresh vs wedged either way."""
    age = Heartbeat.age_s(heartbeat_path)
    if age is None:
        return {"status": "missing", "heartbeat": heartbeat_path}
    if events_path is None:
        events_path = os.path.join(
            os.path.dirname(os.path.abspath(heartbeat_path)), "events.jsonl")
    median = recent_median_step_wall(events_path)
    threshold = max(min_age, factor * median) if median else min_age
    verdict: Dict[str, Any] = {
        "status": "stalled" if age > threshold else "alive",
        "mode": "heartbeat",
        "heartbeat": heartbeat_path,
        "age_s": round(age, 3),
        "threshold_s": round(threshold, 3),
        "median_step_wall_s": round(median, 6) if median else None,
        "factor": factor,
        "min_age_s": min_age,
    }
    # per-replica cadence: the breakdown always ships; a recent lane also
    # overrides a stale heartbeat
    _apply_replica_backstop(verdict, events_path, factor, min_age)
    verdict["events"] = events_path \
        if (median or verdict.get("replicas")) else None
    payload = Heartbeat.read(heartbeat_path)
    if payload:
        verdict["last_beat"] = payload
    return verdict


_EXIT_OF_STATUS = {"alive": 0, "missing": 2, "stalled": 3}


def judge_pod(urls: List[str], events_path: Optional[str] = None,
              factor: float = 10.0, min_age: float = 60.0,
              hbm_warn_pct: float = 90.0) -> Dict[str, Any]:
    """One verdict per ``--url`` target plus a pod roll-up: each target is
    judged independently by :func:`judge_url` (so a wedged backend cannot
    hide behind a healthy router, and vice versa), and the pod status is
    the WORST individual verdict — stalled beats missing beats alive —
    because a supervisor acting on the exit code must react to the
    sickest process, not the average one."""
    targets: Dict[str, Any] = {}
    worst = "alive"
    for url in urls:
        v = judge_url(url, events_path=events_path, factor=factor,
                      min_age=min_age, hbm_warn_pct=hbm_warn_pct)
        targets[url] = v
        if _EXIT_OF_STATUS[v["status"]] > _EXIT_OF_STATUS[worst]:
            worst = v["status"]
    return {"status": worst, "mode": "pod", "targets": targets}


def render_pod_table(pod: Dict[str, Any]) -> str:
    """The pod staleness table: one row per ``--url`` target with its own
    age-vs-threshold evidence, advisory flags compressed into a notes
    column, and a one-line worst-verdict summary on top."""
    lines = [f"POD {pod['status'].upper()}: "
             f"{len(pod['targets'])} target(s), worst verdict wins"]
    lines.append(f"  {'STATUS':<8} {'STATE':<10} {'AGE':>8} {'THRESH':>8} "
                 f"{'ROLE':<10} TARGET")
    for url, v in pod["targets"].items():
        if v["status"] == "missing":
            note = v.get("error", "no liveness signal")
            lines.append(f"  {'MISSING':<8} {'-':<10} {'-':>8} {'-':>8} "
                         f"{'-':<10} {url}  [{note}]")
            continue
        notes = []
        if v.get("alive_via"):
            notes.append(f"alive via {v['alive_via']}")
        stale = [bid for bid, b in (v.get("backends") or {}).items()
                 if not b["recent"]]
        if stale:
            notes.append("stale backends: " + ", ".join(stale))
        if (v.get("model") or {}).get("rollout"):
            notes.append(f"rollout {v['model']['rollout'].get('phase')}")
        if v.get("hbm_warning"):
            notes.append("HBM pressure")
        if (v.get("store") or {}).get("state") == "DEGRADED":
            notes.append("store DEGRADED")
        tail = ("  [" + "; ".join(notes) + "]") if notes else ""
        lines.append(f"  {v['status'].upper():<8} "
                     f"{str(v.get('state')):<10} "
                     f"{v['age_s']:>7.1f}s {v['threshold_s']:>7.1f}s "
                     f"{str(v.get('role')):<10} {url}{tail}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Judge a training run's or serving process's liveness "
                    "from its heartbeat file + event log, or remotely via "
                    "the serving introspection plane (--url)")
    ap.add_argument("heartbeat", nargs="?", default=None,
                    help="path to heartbeat.json (omit when using --url)")
    ap.add_argument("--url", action="append", default=None, metavar="URL",
                    help="poll a serving process's /healthz instead of a "
                         "heartbeat file (base URL or full /healthz URL) — "
                         "the cross-host mode; --events still feeds the "
                         "cadence threshold + replica backstop when the "
                         "log is readable from here.  Repeat the flag to "
                         "judge a whole pod in one invocation: one "
                         "staleness table, worst verdict as exit status")
    ap.add_argument("--events", default=None,
                    help="event log for the step-wall cadence (default: "
                         "events.jsonl beside the heartbeat file; no "
                         "default in --url mode)")
    ap.add_argument("--factor", type=float, default=10.0,
                    help="stall threshold = factor x median step wall "
                         "(default 10)")
    ap.add_argument("--min-age", type=float, default=60.0,
                    help="threshold floor in seconds (default 60; also the "
                         "whole threshold when no step cadence is readable)")
    ap.add_argument("--hbm-warn-pct", type=float, default=90.0,
                    help="(--url mode) warn — never flag STALLED — when any "
                         "replica's HBM fill exceeds this percent "
                         "(default 90)")
    ap.add_argument("--json", action="store_true",
                    help="emit the verdict as one JSON document")
    args = ap.parse_args(argv)
    if (args.heartbeat is None) == (not args.url):
        ap.error("give exactly one of: a heartbeat path, or --url")

    if args.url and len(args.url) > 1:
        pod = judge_pod(args.url, events_path=args.events,
                        factor=args.factor, min_age=args.min_age,
                        hbm_warn_pct=args.hbm_warn_pct)
        if args.json:
            print(json.dumps(pod, indent=2, sort_keys=True))
        else:
            print(render_pod_table(pod))
        return _EXIT_OF_STATUS[pod["status"]]

    if args.url:
        verdict = judge_url(args.url[0], events_path=args.events,
                            factor=args.factor, min_age=args.min_age,
                            hbm_warn_pct=args.hbm_warn_pct)
    else:
        verdict = judge(args.heartbeat, events_path=args.events,
                        factor=args.factor, min_age=args.min_age)
    if args.json:
        print(json.dumps(verdict, indent=2, sort_keys=True))
    elif verdict["status"] == "missing":
        where = verdict.get("heartbeat") or verdict.get("url")
        print(f"no liveness signal at {where} (run not started, telemetry "
              "off, endpoint unreachable, or already cleaned up)"
              + (f" [{verdict['error']}]" if verdict.get("error") else ""))
    else:
        cadence = (f"median step wall {verdict['median_step_wall_s']}s "
                   f"x {verdict['factor']}"
                   if verdict["median_step_wall_s"]
                   else f"no step cadence; floor {verdict['min_age_s']}s")
        via = (f" [alive via {verdict['alive_via']}]"
               if verdict.get("alive_via") else "")
        if verdict.get("mode") == "url":
            print(f"{verdict['status'].upper()}{via}: activity age "
                  f"{verdict['age_s']}s vs threshold "
                  f"{verdict['threshold_s']}s ({cadence}); service state "
                  f"{verdict.get('state')} at {verdict['url']}")
        else:
            beat = verdict.get("last_beat") or {}
            print(f"{verdict['status'].upper()}{via}: heartbeat age "
                  f"{verdict['age_s']}s vs threshold "
                  f"{verdict['threshold_s']}s "
                  f"({cadence}); last beat: step {beat.get('step')}, "
                  f"pid {beat.get('pid')}, run {beat.get('run')}")
        for rid, r in (verdict.get("replicas") or {}).items():
            tag = "fresh" if r["recent"] else "wedged/idle"
            print(f"  replica {rid}: last batch "
                  f"{r['last_batch_age_s']}s ago vs {r['threshold_s']}s "
                  f"({tag}; n={r['n']})")
        for bid, b in (verdict.get("backends") or {}).items():
            tag = "fresh" if b["recent"] else "wedged/idle"
            print(f"  backend {bid} [{b.get('state')}]: last result "
                  f"{b['last_result_age_s']}s ago vs {b['threshold_s']}s "
                  f"({tag})")
        mv = verdict.get("model")
        if mv:
            ro = mv.get("rollout")
            if ro:
                phase = ro.get("phase")
                vers = (f"({ro.get('old_version')} -> "
                        f"{ro.get('new_version')})")
                if phase in ("COMPLETE", "ROLLED_BACK"):
                    print(f"  last rollout: {phase} {vers}")
                else:
                    print(f"  rollout in progress: {phase} {vers} — mixed "
                          "versions and one DRAINING replica are expected "
                          "here, not trouble")
            if mv.get("mixed_versions"):
                print("  MIXED-VERSION pod: "
                      + ", ".join(mv["mixed_versions"]))
            elif mv.get("model_version") and not ro:
                print(f"  model version: {mv['model_version']}")
        rt = verdict.get("retrieval")
        if rt:
            print(f"  retrieval pod: {rt.get('shards_ready')}/"
                  f"{rt.get('shards_total')} shards ready (R="
                  f"{rt.get('replication')}); coverage p50 "
                  f"{rt.get('coverage_p50')}, min {rt.get('coverage_min')} "
                  f"vs floor {rt.get('min_coverage')} — answers below the "
                  "floor arrive DEGRADED, never silent")
        hw = verdict.get("hbm_warning")
        if hw:
            for rid, s in hw["replicas"].items():
                print(f"  WARNING: replica {rid} HBM {s['fill_pct']}% full "
                      f"(>= {hw['threshold_pct']}%; "
                      f"{s.get('bytes_in_use')}/{s.get('bytes_limit')} "
                      "bytes) — pressure, not a stall")
        st = verdict.get("store")
        if st:
            if st.get("state") == "DEGRADED":
                print(f"  WARNING: feature store DEGRADED "
                      f"({st.get('reason')}; "
                      f"degraded_ops={st.get('degraded_ops')}) — failing "
                      "open to recompute: degraded-but-serving, NOT a "
                      "stall")
            else:
                hp = st.get("hit_pct")
                print(f"  feature store {st.get('state')}"
                      + (f" (hit% {hp})" if hp is not None else ""))
    return _EXIT_OF_STATUS[verdict["status"]]


if __name__ == "__main__":
    raise SystemExit(main())
