#!/usr/bin/env python
"""Variant timings at the SHARDED filter's conv shapes (VERDICT r4 item 8).

The hB-sharded path (parallel/spatial.py `_nc_stack_sharded`) re-enters
``choose_conv4d_variant`` with shapes the chooser's measurements never
covered: per-shard volumes with a halo-padded hB and ``pad_hb=False``
(valid conv).  The chooser's decision depends only on shapes — not on the
mesh — so the per-shard convs can be timed honestly on ONE chip by feeding
inputs at exactly the halo-padded shapes the shards see.

Workload: the canonical InLoc case (image 3200, k=2 pooled 56M-cell volume
(1,100,75,100,75), IVD arch k=3 with the tap-swap-fused first layer
1→32ch), hB=100 sharded 8 ways → per-shard hB_local=13 (+pad to 104/8) + 1
halo each side.  Composed structure mirrors `_neigh_consensus_sharded`'s
fused branch: L1 (1→32, pad_hb=False) → relu → halo-shape L2 twins
(16→1 ×2, pad_hb=False) → sum.

Result (v5e, r5, bf16, ms/shard-pass): auto(tapfold,coutfold) 4.76 —
already the fastest; L1=coutfold 7.2, L1=unroll 8.9, L2=unroll 10.9,
L2=tapfold 18.1, both-unroll 19.9.  The chooser's routing HOLDS at the
halo-padded valid-conv shapes; no pin needed.

Usage: python tools/sharded_variant_probe.py
"""

import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")

from _timing import timeit  # noqa: E402

DT = jnp.bfloat16
# per-shard InLoc shape: hB 100 -> pad 104 -> 13 local (+2*halo(k=3)=1)
HA, WA, HB_LOC, WB = 100, 75, 13, 75
HALO = 1
K = 3
C = 16


def make_input(key):
    ks = jax.random.split(key, 4)
    x = jax.random.normal(ks[0], (1, HA, WA, HB_LOC + 2 * HALO, WB, 1), DT) * 0.1
    w1 = jax.random.normal(ks[1], (K,) * 4 + (1, 2 * C), DT) * 0.1
    w2a = jax.random.normal(ks[2], (K,) * 4 + (C, 1), DT) * 0.1
    w2b = jax.random.normal(ks[3], (K,) * 4 + (C, 1), DT) * 0.1
    return x, w1, w2a, w2b


def make_step(v1, v2):
    from ncnet_tpu.ops.conv4d import conv4d

    def step(carry):
        x, w1, w2a, w2b = carry
        y = jax.nn.relu(conv4d(x, w1, pad_hb=False, variant=v1))
        # the production path re-halos between layers (ppermute); the
        # single-chip stand-in pads the SAME number of rows so L2 sees the
        # identical shape class
        yp = jnp.pad(y, ((0, 0),) * 3 + ((HALO, HALO),) + ((0, 0),) * 2)
        out = jax.nn.relu(conv4d(yp[..., :C], w2a, pad_hb=False, variant=v2)) \
            + jax.nn.relu(conv4d(yp[..., C:], w2b, pad_hb=False, variant=v2))
        eps = (jnp.sum(out.astype(jnp.float32)) * 1e-12).astype(x.dtype)
        return x + eps, w1, w2a, w2b

    return step


def main():
    from ncnet_tpu.ops.conv4d import choose_conv4d_variant

    auto1 = choose_conv4d_variant(
        1, 2 * C, HB_LOC + 2 * HALO, WB, shape_a=(HA, WA), kernel=(K,) * 4,
        same_pad=False, dtype=DT, batch=1,
    )
    auto2 = choose_conv4d_variant(
        C, 1, HB_LOC, WB, shape_a=(HA, WA), kernel=(K,) * 4,
        same_pad=False, dtype=DT, batch=1,
    )
    print(f"device={jax.devices()[0].device_kind}  "
          f"auto routing: L1={auto1} L2={auto2}")

    combos = [
        ("auto", ("auto", "auto")),
        (f"pinned auto ({auto1},{auto2})", (auto1, auto2)),
        ("L1=coutfold", ("coutfold", auto2)),
        ("L1=unroll", ("unroll", auto2)),
        ("L2=unroll", (auto1, "unroll")),
        ("L2=tapfold", (auto1, "tapfold")),
        ("both unroll", ("unroll", "unroll")),
    ]
    for name, (v1, v2) in combos:
        try:
            ms = timeit(make_step(v1, v2), make_input, n_long=6)
            print(f"{name:>28}: {ms:8.3f} ms/shard-pass")
        except Exception as e:
            print(f"{name:>28}: ERR {str(e)[:80]}")


if __name__ == "__main__":
    main()
