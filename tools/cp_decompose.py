#!/usr/bin/env python
"""Convert a trained dense checkpoint to rank-R CP factors (ISSUE 17).

The CLI wrapper over ``ncnet_tpu/ops/cp_als.py`` (HOSVD init + ALS
refinement, per-mode exact least squares): loads a checkpoint written by
``models/checkpoint.py``, attaches a ``"cp"`` factor dict beside every NC
layer's dense ``"w"``/``"b"`` (the ``"cp"`` tier's opt-in signal —
ops/conv4d_cp.py), and writes a new checkpoint.  The dense kernels stay,
so the converted checkpoint still serves every non-CP tier and the
chooser falls back freely where the CP gate loses.

Accuracy lost at low rank is recovered by fine-tuning the factors with
the trunk frozen — ``train.py --finetune_cp_rank R`` (the Lebedev et al.
recipe) — which performs this conversion in-memory on its own loaded
checkpoint; this tool exists for offline conversion and for inspecting
per-layer reconstruction error vs rank before committing to one.

Usage::

    python tools/cp_decompose.py --checkpoint trained_models/ckpt \
        --out trained_models/ckpt_cp --rank 16 [--iters 60] [--json]

Exit codes: 0 = converted (per-layer relative errors reported), 2 =
usage/input error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ncnet_tpu.ops.cp_als import (  # noqa: E402
    DEFAULT_ALS_ITERS,
    decompose_stack,
)

_out = sys.stdout.write
_err = sys.stderr.write


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--checkpoint", required=True,
                    help="trained checkpoint dir (models/checkpoint.py)")
    ap.add_argument("--out", required=True,
                    help="output checkpoint dir (config + params with CP "
                         "factors attached)")
    ap.add_argument("--rank", type=int, default=None,
                    help="CP rank (default: ops/conv4d_cp.DEFAULT_CP_RANK)")
    ap.add_argument("--iters", type=int, default=DEFAULT_ALS_ITERS,
                    help="ALS refinement sweeps")
    ap.add_argument("--json", action="store_true",
                    help="emit a machine-readable summary line")
    args = ap.parse_args(argv)

    from ncnet_tpu.config import ModelConfig
    from ncnet_tpu.models.checkpoint import load_params, save_params
    from ncnet_tpu.ops.conv4d_cp import DEFAULT_CP_RANK

    rank = args.rank if args.rank is not None else DEFAULT_CP_RANK
    if rank < 1:
        _err(f"--rank must be >= 1, got {rank}\n")
        return 2
    try:
        config, params = load_params(args.checkpoint, ModelConfig())
    except (OSError, ValueError) as e:
        _err(f"cannot load checkpoint {args.checkpoint!r}: {e}\n")
        return 2
    params = dict(params)
    params["nc"], errs = decompose_stack(params["nc"], rank,
                                         iters=args.iters)
    save_params(args.out, config, params)
    if args.json:
        _out(json.dumps({"rank": rank, "iters": args.iters,
                         "rel_errs": errs, "out": args.out}) + "\n")
    else:
        _out(f"rank={rank} iters={args.iters}\n")
        for i, err in enumerate(errs):
            _out(f"  nc layer {i}: relative reconstruction error "
                 f"{err:.4f}\n")
        _out(f"wrote {args.out}\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
