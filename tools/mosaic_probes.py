#!/usr/bin/env python
"""Probe which Mosaic lowerings the installed toolchain accepts.

The round-2 Pallas conv4d kernel was rejected with "unsupported shape cast"
on lane-dim splits/merges.  Before redesigning the kernel, compile a battery
of minimal kernels that each exercise ONE layout-sensitive operation, so the
redesign composes only known-good primitives.  Run on the real TPU:

    python tools/mosaic_probes.py            # all probes
    python tools/mosaic_probes.py lane_merge # one probe

Prints one PASS/FAIL line per probe (+ first error line on FAIL).
"""

import functools
import sys

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DT = jnp.bfloat16


def _compile(kernel, out_shape, *in_shapes):
    def run(*xs):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(out_shape, DT),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM) for _ in in_shapes],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        )(*xs)

    args = [jax.ShapeDtypeStruct(s, DT) for s in in_shapes]
    jax.jit(run).lower(*args).compile()


# Shapes chosen to mirror the conv4d kernel's regime: c=16 channels,
# l=29 B-columns (25 + halo), fused minor (l*c)=464.
C, L, ROWS = 16, 29, 32


def probe_lane_merge():
    """reshape (rows, L, C) -> (rows, L*C): merge into the lane dim."""
    def k(x_ref, o_ref):
        o_ref[:] = x_ref[:].reshape(ROWS, L * C)
    _compile(k, (ROWS, L * C), (ROWS, L, C))


def probe_lane_split():
    """reshape (rows, L*C) -> (rows, L, C): split the lane dim."""
    def k(x_ref, o_ref):
        o_ref[:] = x_ref[:].reshape(ROWS, L, C)
    _compile(k, (ROWS, L, C), (ROWS, L * C))


def probe_lane_concat():
    """concatenate two C-lane tensors along lanes."""
    def k(x_ref, y_ref, o_ref):
        o_ref[:] = jnp.concatenate([x_ref[:], y_ref[:]], axis=-1)
    _compile(k, (ROWS, 2 * C), (ROWS, C), (ROWS, C))


def probe_lane_concat_wide():
    """concatenate five 464-lane tensors along lanes (tapfold P build)."""
    def k(*refs):
        o_ref = refs[-1]
        o_ref[:] = jnp.concatenate([r[:] for r in refs[:-1]], axis=-1)
    _compile(k, (ROWS, 5 * L * C), *([(ROWS, L * C)] * 5))


def probe_lane_pad():
    """pad the lane dim C -> 128."""
    def k(x_ref, o_ref):
        o_ref[:] = jnp.pad(x_ref[:], ((0, 0), (0, 128 - C)))
    _compile(k, (ROWS, 128), (ROWS, C))


def probe_lane_slice_offset():
    """static lane slice at a 16-aligned, non-128-aligned offset."""
    def k(x_ref, o_ref):
        o_ref[:] = x_ref[:, C : C + 8 * C]
    _compile(k, (ROWS, 8 * C), (ROWS, L * C))


def probe_lane_slice_unaligned():
    """static lane slice at an odd offset (epilogue c_out=1 case)."""
    def k(x_ref, o_ref):
        o_ref[:] = x_ref[:, 3 : 3 + 128]
    _compile(k, (ROWS, 128), (ROWS, L * C))


def probe_lane_store_offset():
    """store into a lane sub-range of the output."""
    def k(x_ref, o_ref):
        o_ref[:, :] = jnp.zeros((ROWS, L * C), DT)
        o_ref[:, C : C + C] = x_ref[:]
    _compile(k, (ROWS, L * C), (ROWS, C))


def probe_lane_roll():
    """pltpu.roll along the lane dim."""
    def k(x_ref, o_ref):
        o_ref[:] = pltpu.roll(x_ref[:], 16, 1)
    _compile(k, (ROWS, 128), (ROWS, 128))


def probe_sublane_slice():
    """slice the sublane dim at an arbitrary offset."""
    def k(x_ref, o_ref):
        o_ref[:] = x_ref[3 : 3 + 16, :]
    _compile(k, (16, L * C), (ROWS, L * C))


def probe_sublane_merge():
    """reshape merging a leading dim into sublanes (5, 8, lanes)->(40, lanes)."""
    def k(x_ref, o_ref):
        o_ref[:] = x_ref[:].reshape(5 * ROWS, L * C)
    _compile(k, (5 * ROWS, L * C), (5, ROWS, L * C))


def probe_sublane_split():
    """reshape splitting sublanes into a leading dim."""
    def k(x_ref, o_ref):
        o_ref[:] = x_ref[:].reshape(5, ROWS, L * C)
    _compile(k, (5, ROWS, L * C), (5 * ROWS, L * C))


def probe_leading_stack():
    """jnp.stack along a new leading axis."""
    def k(x_ref, y_ref, o_ref):
        o_ref[:] = jnp.stack([x_ref[:], y_ref[:]], axis=0)
    _compile(k, (2, ROWS, L * C), (ROWS, L * C), (ROWS, L * C))


def probe_dot_contract_sublane():
    """dot_general contracting dim 0 of both operands: (K,N)x(K,M)->(N,M)."""
    def k(w_ref, a_ref, o_ref):
        o_ref[:] = jax.lax.dot_general(
            w_ref[:], a_ref[:], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(DT)
    _compile(k, (400, 512), (2000, 400), (2000, 512))


def probe_dot_plain():
    """plain (M,K)@(K,N) dot at conv4d-like shape."""
    def k(a_ref, w_ref, o_ref):
        o_ref[:] = jnp.dot(
            a_ref[:], w_ref[:], preferred_element_type=jnp.float32
        ).astype(DT)
    _compile(k, (512, 400), (512, 2000), (2000, 400))


def probe_old_kernel():
    """the round-2 conv4d kernel itself (did Mosaic move since?)."""
    from ncnet_tpu.ops.conv4d_pallas import pallas_compiles
    pallas_compiles.cache_clear()
    ok = pallas_compiles(25, 25, 25, 25, 16, 1, 5, dtype_name="bfloat16")
    if not ok:
        raise RuntimeError("pallas_compiles -> False")



# --- r5 probes: primitives for the fused-(hB*wB)-lane NC-stack kernel ---
# Layout: tiles (J, C=16 sublane-blocks, 841 fused-kl lanes); K=(p,q,c)=400.

KL, CC, JJ = 841, 16, 5


def probe_r5_sublane_offset_store_3d():
    """store a (J,16,KL) slab at a 16-aligned sublane offset of (J,400,KL)."""
    def k(x_ref, o_ref):
        o_ref[:] = jnp.zeros((JJ, 400, KL), DT)
        o_ref[:, 32:48, :] = x_ref[:]
    _compile(k, (JJ, 400, KL), (JJ, CC, KL))


def probe_r5_lane_shift_add_3d():
    """arbitrary-lane-offset slice of a 3D tile + accumulate (epilogue)."""
    def k(x_ref, o_ref):
        acc = jnp.zeros((JJ, CC, 721), jnp.float32)
        for off in (0, 33, 60, 120):
            acc = acc + x_ref[:, :, off:off + 721].astype(jnp.float32)
        o_ref[:] = acc.astype(DT)
    _compile(k, (JJ, CC, 721), (JJ, CC, KL))


def probe_r5_dot_k400():
    """dot_general contracting dim0 of both: (400,400)x(400,841)->(400,841)."""
    def k(w_ref, a_ref, o_ref):
        o_ref[:] = jax.lax.dot_general(
            w_ref[:], a_ref[:], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(DT)
    _compile(k, (400, KL), (400, 400), (400, KL))


def probe_r5_dot_rhs3d():
    """dot with a 3D rhs free dim: (400,400)x(J,400,841)->(400,J,841)."""
    def k(w_ref, a_ref, o_ref):
        o_ref[:] = jax.lax.dot_general(
            w_ref[:], a_ref[:], (((0,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(DT)
    _compile(k, (400, JJ, KL), (400, 400), (JJ, 400, KL))


def probe_r5_lane_mask_mul():
    """multiply a (J,16,841) tile by a (1,1,841) lane mask (halo zeroing)."""
    def k(x_ref, m_ref, o_ref):
        o_ref[:] = x_ref[:] * m_ref[:]
    _compile(k, (JJ, CC, KL), (JJ, CC, KL), (1, 1, KL))


def probe_r5_bias_sublane_broadcast():
    """add a per-sublane bias (1,16,1) to a (J,16,841) tile (+ relu)."""
    def k(x_ref, b_ref, o_ref):
        o_ref[:] = jnp.maximum(x_ref[:] + b_ref[:], 0)
    _compile(k, (JJ, CC, KL), (JJ, CC, KL), (1, CC, 1))


def probe_r5_leading_index_dot():
    """leading-index a (J,400,KL) scratch then 2D dot per j (static loop)."""
    def k(w_ref, a_ref, o_ref):
        for j in range(JJ):
            o_ref[j] = jax.lax.dot_general(
                w_ref[:], a_ref[j], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).astype(DT)
    _compile(k, (JJ, 400, KL), (400, 400), (JJ, 400, KL))


def probe_r5_leading_slab_copy():
    """copy a leading-dim slab between 3D refs (A-build primitive)."""
    def k(x_ref, o_ref):
        o_ref[:] = jnp.zeros((JJ, 400, KL), DT)
        for pq in range(4):
            o_ref[:, pq * CC:(pq + 1) * CC, :] = x_ref[pq:pq + JJ, :, :]
    _compile(k, (JJ, 400, KL), (JJ + 4, CC, KL))


PROBES = {
    n[len("probe_"):]: f
    for n, f in sorted(globals().items())
    if n.startswith("probe_")
}


def main():
    names = sys.argv[1:] or list(PROBES)
    for n in names:
        try:
            PROBES[n]()
            print(f"PASS {n}")
        except Exception as e:
            msg = str(e).split("\n")[0][:160]
            print(f"FAIL {n}: {msg}")


if __name__ == "__main__":
    main()
