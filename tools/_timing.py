"""Shared scan-differenced device-timing harness for the tools/ probes.

The device tunnel both caches repeated identical executions and charges
host→device upload to the first execution touching a fresh buffer, so naive
repeat-loops measure either ~0 or the transfer.  ``timeit`` jits a program
that generates its input ON DEVICE from a PRNG key and runs the step ``n``
times inside a data-dependent ``lax.scan``, reporting
``(t[n_long] − t[1]) / (n_long − 1)`` medians over fresh keys.

IMPORTANT probe hygiene, learned the hard way (see the project PARITY notes):
  * the step function must fold EVERY output it means to measure back into
    the carry — anything not consumed is dead-code-eliminated, silently
    excluding its compute from the timing;
  * correlation volumes must be BORN from an einsum like production (a raw
    random-normal volume makes XLA pick pathological layouts for the
    maxpool4d 8D reshape — a 66×-padded 119 GB allocation);
  * standalone formulation timings are hypotheses only — the composed
    program is the unit of measurement.
"""

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def timeit(step_fn, make_input, n_long=6, reps=3, per=1):
    """Steady-state ms per ``step_fn`` call (divided by ``per``).

    ``step_fn(carry) -> carry`` must keep the carry's structure/shape;
    ``make_input(key)`` builds the initial carry on device.
    """

    @partial(jax.jit, static_argnums=(1,))
    def run(key, n):
        def body(x, _):
            return step_fn(x), ()

        x, _ = lax.scan(body, make_input(key), None, length=n)
        return jnp.sum(jax.tree.leaves(x)[0].astype(jnp.float32))

    key = jax.random.key
    float(run(key(0), 1))
    float(run(key(1), n_long))  # compile both lengths
    diffs = []
    for i in range(reps):
        t0 = time.perf_counter()
        float(run(key(100 + i), 1))
        t1 = time.perf_counter()
        float(run(key(200 + i), n_long))
        t2 = time.perf_counter()
        diffs.append(((t2 - t1) - (t1 - t0)) / (n_long - 1) * 1e3)
    return float(np.median([max(d, 0.0) for d in diffs])) / per
