#!/usr/bin/env python
"""Time the COMPOSED production train step across backward-path knobs.

The round-3 verdict localized the training cost in the backward (816 ms/step
@ bs8 fp32 vs ~94 ms of batch forward) and prescribed two levers: folding the
positive/negative volumes into one 2B-batch filter pass, and per-layer
gradient-formulation choice.  This probe measures the real
``make_train_step`` program (donated state, optimizer included) under each
knob combination, per the probe law: standalone numbers are hypotheses only —
the composed program is the unit of measurement.

Usage: python tools/train_probe.py [batch] [dtype] [combo ...]
  combo: name=fold,remat_filter,remat_layers,custom  (y/n each), e.g.
         base=n,y,n,n fold=y,y,n,n fold_noremat=y,n,n,n
  default sweep: base, fold, noremat, fold_noremat
"""

import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")

from _timing import timeit  # noqa: E402

from ncnet_tpu.config import ModelConfig, TrainConfig  # noqa: E402
from ncnet_tpu.training import train as tr  # noqa: E402

B = int(sys.argv[1]) if len(sys.argv) > 1 else 8
DT_HALF = len(sys.argv) > 2 and sys.argv[2] == "bf16"
BF16_TRUNK = len(sys.argv) > 2 and sys.argv[2] == "fp32bt"  # fp32 volume, bf16 trunk
SIZE = 400

COMBOS = []
for arg in sys.argv[3:]:
    name, spec = arg.split("=")
    parts = spec.split(",")
    fold, rf, rl, cg = [s == "y" for s in parts[:4]]
    chunks = int(parts[4]) if len(parts) > 4 else 0
    COMBOS.append((name, fold, rf, rl, cg, chunks))
if not COMBOS:
    COMBOS = [
        ("base", False, True, False, False, 0),
        ("fold", True, True, False, False, 0),
        ("noremat", False, False, False, False, 0),
        ("fold_noremat", True, False, False, False, 0),
    ]


def main():
    mcfg = ModelConfig(
        ncons_kernel_sizes=(5, 5, 5), ncons_channels=(16, 16, 1),
        half_precision=DT_HALF, backbone_bf16=BF16_TRUNK,
    )
    tcfg = TrainConfig(model=mcfg, batch_size=B, data_parallel=False)
    state, optimizer, mcfg, _ = tr.create_train_state(tcfg)
    params = state.params

    for name, fold, rf, rl, cg, chunks in COMBOS:
        step = tr.make_train_step(
            mcfg, optimizer, donate=False,  # scan carry already reuses buffers
            stop_backbone_grad=True, remat_nc_layers=rl, nc_custom_grad=cg,
            fold_pos_neg=fold, remat_filter=rf, accum_chunks=chunks,
        )

        def tick(carry, _step=step):
            # src is leaves[0] (the harness's consumed output): fold the loss
            # AND a trainable-param summary into it so neither the filter
            # backward nor the optimizer update can be DCE'd out of the scan
            src, tgt, st = carry
            st2, loss = _step(st, {"source_image": src, "target_image": tgt})
            psum = jnp.sum(st2.params["nc"][0]["w"].astype(jnp.float32))
            src = src + (loss * 1e-9 + psum * 1e-12).astype(src.dtype)
            return (src, tgt, st2)

        def make_input(key):
            k1, k2 = jax.random.split(key)
            src = jax.random.uniform(k1, (B, SIZE, SIZE, 3), jnp.float32)
            tgt = jax.random.uniform(k2, (B, SIZE, SIZE, 3), jnp.float32)
            return (src, tgt, state)

        try:
            ms = timeit(tick, make_input, n_long=4, reps=3)
            print(f"{name:16s} {ms:8.1f} ms/step  {B / (ms * 1e-3):6.2f} pairs/s",
                  flush=True)
        except Exception as e:  # noqa: BLE001 — print and continue the sweep
            print(f"{name:16s} FAILED: {str(e)[:300]}", flush=True)


if __name__ == "__main__":
    main()
