#!/usr/bin/env python
"""Match-quality drift sentinel: gate a run's quality-signal distributions
against the committed reference (``perf/quality_ref.jsonl``).

The quality layer (``ncnet_tpu/observability/quality.py``) streams per-pair
label-free signals — softmax score, match entropy, top1-top2 margin, hard
mutual-NN agreement, displacement coherence — into the event log as
``quality`` events tagged with the active fused tier.  This tool is the gate
on top, the accuracy twin of ``tools/perf_regress.py``:

  * ``--check``: rebuild per-``(tier, signal)`` histogram digests from one
    or more event logs (binned exactly like the reference), score each
    against the committed reference distribution for the log's device kind
    with a PSI divergence (< 0.1 no shift, 0.1-0.25 moderate, > 0.25
    major — the default threshold), and **exit 1 on drift**.  A bf16 tier
    promotion, a CP/FFT conv4d prototype, or a quarantine-degraded run that
    shifts match quality fails the job between labeled evals — this is the
    standing accuracy gate new kernel tiers run under (ROADMAP items 2-4).
  * ``--seed-ref``: (re)write the reference file from event logs of a CLEAN
    eval of the committed weights, or — with ``--synthetic`` — from the
    pinned deterministic synthetic PF-Pascal CPU eval this repo's tier-1
    tests replay (the committed ``perf/quality_ref.jsonl`` is produced this
    way; README "Quality observability" documents the re-seed policy).

Usage::

    python tools/quality_drift.py --check events.jsonl [more.jsonl ...]
        [--ref perf/quality_ref.jsonl] [--threshold 0.25] [--json]
    python tools/quality_drift.py --seed-ref events.jsonl [--ref ...]
    python tools/quality_drift.py --seed-ref --synthetic [--ref ...]

Exit codes: 0 = no drift (or seed OK; unjudgeable series are reported as
skipped, never guessed), 1 = drift detected, 2 = usage/input error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ncnet_tpu.observability.events import replay_events  # noqa: E402
from ncnet_tpu.observability.quality import (  # noqa: E402
    DEFAULT_PSI_THRESHOLD,
    check_drift,
    default_reference_path,
    digests_from_events,
    load_reference,
    reference_binning,
    write_reference,
)

_out = sys.stdout.write
_err = sys.stderr.write

# the pinned synthetic fixture: what the committed reference was seeded from
# and what the tier-1 drift test replays.  Changing ANY of these re-defines
# the reference distribution — re-seed perf/quality_ref.jsonl in the same
# commit.
SYNTH_SEED = 11
SYNTH_PAIRS = 12
SYNTH_SCRAMBLED = 4          # trailing pairs whose target is unrelated
SYNTH_IMAGE_HW = (96, 96)
SYNTH_SHIFT = (16, 16)
SYNTH_BATCH = 2
# the coarse-to-fine tier's pinned fixture knobs: the same eval re-run with
# ModelConfig.sparse_topk = this k seeds/gates the "coarse2fine" series
# (6x6 feature grid -> 3x3 coarse grid; k=2 of 9 candidates).  The sparse
# fixture's shift is COARSE-ALIGNED (2 fine cells = 1 coarse cell at
# factor 2): the pooled coarse correlation is then crisp, top-k coverage
# contains the true cells, and the sparse confident pairs score PCK 1.0
# exactly like dense — the fixture demonstrates the lossless-under-coverage
# regime rather than the tiny 3x3 grid's pooling blur.
SYNTH_SPARSE_K = 2
SYNTH_SPARSE_SHIFT = (32, 32)


def synthetic_reference_run(workdir: str, perturb: bool = False,
                            sparse: bool = False, tier: str = ""):
    """Run the pinned deterministic synthetic PF-Pascal eval on this
    backend; returns ``(stats, events_path)``.

    The fixture mixes confident pairs (exact feature-cell shifts the
    identity NC stack recovers, PCK ~1) with scrambled pairs (unrelated
    target textures: diffuse match distributions, PCK ~0), so the
    signal-vs-PCK rank correlation is measurable and the reference
    distribution spans both regimes.  Everything is seed-pinned — dataset,
    trunk init, loader order — so two runs on one backend produce
    bit-identical signals, which is what lets the committed reference gate
    at PSI ≈ 0.

    ``perturb=True`` coarsely quantizes the filtered volume before match
    extraction — the injected stand-in for a low-precision kernel-tier
    regression the drift gate must flag.

    ``sparse=True`` re-runs the same pinned fixture through the
    coarse-to-fine sparse pipeline (``ModelConfig.sparse_topk =
    SYNTH_SPARSE_K``): its quality events are tier-tagged ``coarse2fine``,
    which seeds — and then gates — that tier's own reference series (the
    label-free proof the sparse tier loses no accuracy, ISSUE 15).

    ``tier="cp"`` attaches rank-1 CP factors to the NC params (the delta
    kernel is exactly rank 1, and rank 1 clears the arithmetic gate at
    this 6x6/k=3 fixture, so ``choose_fused_stack`` selects "cp"
    NATURALLY); ``tier="fft"`` forces the FFT tier via
    ``ModelConfig.nc_tier`` (the spectral gate rightly rejects k=3 on
    cost grounds — exactness, not speed, is what the reference series
    certifies).  Either way the quality events are tagged with the tier
    name, seeding that tier's own reference series.
    """
    import jax.numpy as jnp
    import numpy as np
    from PIL import Image

    from ncnet_tpu import models
    from ncnet_tpu.config import EvalPFPascalConfig, ModelConfig
    from ncnet_tpu.data.synthetic import _textured_image, write_pf_pascal_like
    from ncnet_tpu.evaluation.pf_pascal import run_eval

    data = os.path.join(workdir, "data")
    shift = SYNTH_SPARSE_SHIFT if sparse else SYNTH_SHIFT
    write_pf_pascal_like(data, n_pairs=SYNTH_PAIRS, image_hw=SYNTH_IMAGE_HW,
                         shift=shift, seed=SYNTH_SEED)
    # scramble the trailing pairs' targets: unrelated texture, keypoints
    # kept — low PCK AND diffuse (low-confidence) match distributions
    rng = np.random.default_rng(SYNTH_SEED + 1)
    h, w = SYNTH_IMAGE_HW
    for i in range(SYNTH_PAIRS - SYNTH_SCRAMBLED, SYNTH_PAIRS):
        Image.fromarray(_textured_image(rng, h, w)).save(
            os.path.join(data, "images", f"test_{i}_b.jpg"), quality=95)

    cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                      ncons_channels=(1,))
    if sparse:
        cfg = cfg.replace(sparse_topk=SYNTH_SPARSE_K)
    if tier == "fft":
        cfg = cfg.replace(nc_tier="fft")
    net = models.NCNet(cfg, seed=0)
    iw = np.zeros((3, 3, 3, 3, 1, 1), np.float32)
    iw[1, 1, 1, 1, 0, 0] = 1.0
    net.params["nc"] = [{"w": jnp.asarray(iw), "b": jnp.zeros((1,))}]
    if tier == "cp":
        from ncnet_tpu.ops.cp_als import decompose_stack

        net.params["nc"], _ = decompose_stack(net.params["nc"], 1)
    if perturb:
        orig = net.forward_fn

        def forward_fn(params, src, tgt):
            out = orig(params, src, tgt)
            # coarse value quantization of the filtered volume: the shape
            # of a numeric-precision regression (scores flatten, margins
            # shrink) without modeling any one kernel's exact rounding
            step = 0.05 * jnp.max(jnp.abs(out.corr))
            return out._replace(
                corr=jnp.round(out.corr / step) * step)

        net.forward_fn = forward_fn

    tdir = os.path.join(workdir, "telemetry")
    ecfg = EvalPFPascalConfig(eval_dataset_path=data, image_size=96,
                              telemetry_dir=tdir)
    # a fixture eval is NOT a perf datapoint: its walls/PCK must never be
    # ingested into the committed cross-run history the regression sentinel
    # gates on (the env knob is restored after the run)
    from ncnet_tpu.observability.perfstore import STORE_ENV

    prev = os.environ.get(STORE_ENV)
    os.environ[STORE_ENV] = "off"
    try:
        stats = run_eval(ecfg, net=net, batch_size=SYNTH_BATCH,
                         num_workers=0, progress=False)
    finally:
        if prev is None:
            os.environ.pop(STORE_ENV, None)
        else:
            os.environ[STORE_ENV] = prev
    return stats, os.path.join(tdir, "events.jsonl")


def _load_logs(paths: List[str]):
    """Replay logs → (device_kind, events).  The device kind comes from the
    first header that names one — digests are only comparable within one
    backend, so it keys the reference lookup."""
    events: List[dict] = []
    device_kind: Optional[str] = None
    for path in paths:
        header, recs = replay_events(path)
        events.extend(recs)
        if device_kind is None:
            device_kind = (header.get("header") or {}).get("device_kind")
    return device_kind, events


def _render(findings: List[dict]) -> str:
    n_drift = sum(1 for f in findings if f["status"] == "drift")
    n_ok = sum(1 for f in findings if f["status"] == "ok")
    n_skip = sum(1 for f in findings if f["status"] == "skipped")
    lines = [f"=== quality_drift: {n_drift} drift(s), {n_ok} ok, "
             f"{n_skip} skipped ==="]
    for f in findings:
        tag = {"drift": "DRIFT", "ok": "ok", "skipped": "skipped"}[f["status"]]
        line = (f"[{tag}] {f['tier']}/{f['signal']} "
                f"({f['device_kind']}): n={f['count']}")
        if f.get("mean") is not None:
            line += f" mean={f['mean']:.4f}"
        if f["status"] == "skipped":
            line += f"  ({f['reason']})"
        else:
            line += (f"  psi={f['psi']:.4f} (threshold {f['threshold']}) "
                     f"ref: n={f['ref_count']} mean={f['ref_mean']:.4f}")
        lines.append(line)
    if not findings:
        lines.append("(no quality events in the given logs)")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Gate quality-signal distributions against the "
                    "committed reference")
    ap.add_argument("logs", nargs="*", help="events.jsonl file(s)")
    ap.add_argument("--ref", default=None,
                    help="reference file (default: perf/quality_ref.jsonl)")
    ap.add_argument("--check", action="store_true",
                    help="judge the logs' digests against the reference; "
                         "exit 1 on drift")
    ap.add_argument("--seed-ref", action="store_true",
                    help="(re)write the reference from the logs (or "
                         "--synthetic)")
    ap.add_argument("--synthetic", action="store_true",
                    help="with --seed-ref: run the pinned synthetic CPU "
                         "eval and seed from it")
    ap.add_argument("--threshold", type=float, default=DEFAULT_PSI_THRESHOLD,
                    help=f"PSI drift threshold (default "
                         f"{DEFAULT_PSI_THRESHOLD})")
    ap.add_argument("--min-count", type=int, default=4,
                    help="samples required before judging a series "
                         "(default 4)")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as one JSON document")
    args = ap.parse_args(argv)

    ref_path = args.ref or default_reference_path()
    if not args.check and not args.seed_ref:
        _err("quality_drift: nothing to do (pass --check and/or "
             "--seed-ref)\n")
        return 2

    logs = list(args.logs)
    if args.seed_ref and args.synthetic:
        import tempfile

        work = tempfile.mkdtemp(prefix="quality_ref_")
        _err(f"running the pinned synthetic reference eval under {work}\n")
        _, events_path = synthetic_reference_run(work)
        # the same pinned fixture through the coarse-to-fine sparse
        # pipeline: seeds the "coarse2fine" tier's own reference series
        # beside the dense tiers' (one file carries every tier the tier-1
        # drift tests gate)
        work_sp = tempfile.mkdtemp(prefix="quality_ref_sparse_")
        _err("running the sparse (coarse2fine) synthetic reference eval "
             f"under {work_sp}\n")
        _, sparse_events = synthetic_reference_run(work_sp, sparse=True)
        # the arithmetic conv4d tiers (CP factors chosen naturally by the
        # gate; FFT forced — see synthetic_reference_run) seed their own
        # series so quality_drift --check gates them like any kernel tier
        work_cp = tempfile.mkdtemp(prefix="quality_ref_cp_")
        _err(f"running the CP-tier synthetic reference eval under "
             f"{work_cp}\n")
        _, cp_events = synthetic_reference_run(work_cp, tier="cp")
        work_fft = tempfile.mkdtemp(prefix="quality_ref_fft_")
        _err(f"running the FFT-tier synthetic reference eval under "
             f"{work_fft}\n")
        _, fft_events = synthetic_reference_run(work_fft, tier="fft")
        logs = [events_path, sparse_events, cp_events, fft_events] + logs

    if not logs:
        _err("quality_drift: no event logs given\n")
        return 2
    try:
        device_kind, events = _load_logs(logs)
    except (OSError, ValueError) as e:
        _err(f"quality_drift: cannot replay logs: {e}\n")
        return 2

    if args.seed_ref:
        digests = digests_from_events(events)
        n = write_reference(
            ref_path, digests, device_kind=device_kind,
            meta={"logs": [os.path.basename(p) for p in logs]},
        )
        _err(f"seeded {n} reference series into {ref_path}\n")
        if not args.check:
            return 0

    reference = load_reference(ref_path)
    if not reference:
        _err(f"quality_drift: reference {ref_path} is missing or empty\n")
        return 2
    # bin the current run exactly like the reference per signal (the ref
    # self-describes its binning)
    current = digests_from_events(
        events, bins_like=reference_binning(reference))
    if not current:
        # an accuracy gate must never report green on zero evidence: a log
        # with no quality events means the emitter is broken or the wrong
        # file was passed — an input error, not a clean run
        _err("quality_drift: no quality events in the given logs — "
             "nothing to judge (broken emitter, or wrong events file?)\n")
        return 2
    findings = check_drift(reference, current, device_kind=device_kind,
                           threshold=args.threshold,
                           min_count=args.min_count)
    if args.json:
        _out(json.dumps({"ref": ref_path, "findings": findings},
                        indent=2, sort_keys=True) + "\n")
    else:
        _out(_render(findings))
    return 1 if any(f["status"] == "drift" for f in findings) else 0


if __name__ == "__main__":
    raise SystemExit(main())
