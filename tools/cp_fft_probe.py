#!/usr/bin/env python
"""CP / FFT arithmetic-tier probe: per-rank, per-shape AOT compile,
memory-ledger and wall characterization against the dense filter tiers.

ISSUE 17's acceptance rides on two measured claims: the CP chain's AOT
temp bytes undercut the dense stack at the production 25⁴/k=5 shape, and
the walls of both arithmetic tiers land where their FLOP gates predict.
This probe produces the evidence:

  * for each requested CP rank: decompose the probe params
    (``ops/cp_als.py``), AOT-compile the forced-CP stack at the given
    volume shape, record its ``memory_analysis()`` into the compiled-
    program memory ledger (program ``cp_fft_probe``, keyed per rank), and
    report temp/peak bytes beside the dense stack program's at the same
    shape — plus the arithmetic gate's verdict (``cp_feasible``) so a
    reader sees where the chooser would actually engage the tier;
  * the FFT tier likewise (``fft_feasible`` + forced-FFT program row);
  * with ``--time`` (TPU session): steady-state walls, each tier vs dense.

``--tiny`` is the CPU smoke kept tier-1 (tests/test_conv4d_tiers.py):
rank-full CP and FFT parity against dense conv4d at miniature shapes,
gate-direction sanity at the production arch, and the 25⁴/k=5 CPU AOT
ledger comparison (CP temp bytes < dense at the default rank) — the
acceptance row itself, runnable with no accelerator.

Usage::

    python tools/cp_fft_probe.py --ranks 4,8,16,32 --size 25 [--time]
    python tools/cp_fft_probe.py --tiny

Exit codes: 0 = OK, 1 = tiny-smoke parity/acceptance failure, 2 = usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

_out = sys.stdout.write
_err = sys.stderr.write


def _params_for(kernels, channels, key_seed=1):
    import jax

    from ncnet_tpu.ops import conv4d_init

    key = jax.random.key(key_seed)
    nc = []
    c_in = 1
    for k, c_out in zip(kernels, channels):
        key, sub = jax.random.split(key)
        w, b = conv4d_init(sub, k, c_in, c_out)
        nc.append({"w": w, "b": b})
        c_in = c_out
    return nc


def _aot_memory(fn, *sds):
    """(compiled, analysis-dict|None) — fail-open where the backend lacks
    ``memory_analysis`` (CPU wheels differ)."""
    import jax

    from ncnet_tpu.observability import memory as obs_memory

    compiled = jax.jit(fn).lower(*sds).compile()
    return compiled, (obs_memory.analysis_dict(compiled) or None)


def _stack_fn(nc_params, tier):
    """A (corr-volume → filtered) single-pass stack through one tier —
    the same ``neigh_consensus`` seam production dispatches through, so
    the compiled program is the production formulation, not a hand-built
    approximation."""
    from ncnet_tpu.models.ncnet import neigh_consensus

    if tier == "dense":
        return lambda p, corr: neigh_consensus(
            p, corr, symmetric=False, allow_pallas=False)
    return lambda p, corr: neigh_consensus(
        p, corr, symmetric=False, force_tier=tier)


def probe(args) -> int:
    import jax
    import jax.numpy as jnp

    from ncnet_tpu.observability import memory as obs_memory
    from ncnet_tpu.ops.conv4d_cp import cp_feasible
    from ncnet_tpu.ops.conv4d_fft import fft_feasible
    from ncnet_tpu.ops.cp_als import decompose_stack

    kernels = tuple(int(v) for v in args.kernels.split(","))
    channels = tuple(int(v) for v in args.channels.split(","))
    ranks = [int(v) for v in args.ranks.split(",")]
    s, b = args.size, args.batch
    dt = jnp.bfloat16 if args.bf16 else jnp.float32
    params = _params_for(kernels, channels)
    if args.bf16:
        params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
    sds = jax.ShapeDtypeStruct((b, s, s, s, s), dt)
    report = {
        "size": s, "batch": b, "kernels": list(kernels),
        "channels": list(channels), "dtype": jnp.dtype(dt).name,
        "device_kind": jax.devices()[0].device_kind,
        "fft_feasible": fft_feasible(s, s, s, s, kernels, channels),
        "ranks": {},
    }

    try:
        _, dense_mem = _aot_memory(_stack_fn(params, "dense"), params, sds)
        report["dense"] = dense_mem
    except Exception as e:  # the dense volume may simply not compile/fit
        report["dense"] = {"error": str(e)[:200]}
        dense_mem = None

    def vs_dense(mem):
        if dense_mem and mem and mem.get("temp_bytes") \
                and dense_mem.get("temp_bytes"):
            return round(mem["temp_bytes"] / dense_mem["temp_bytes"], 4)
        return None

    for rank in ranks:
        row = {"cp_feasible": cp_feasible(
            s, s, s, s, kernels, channels, (rank,) * len(kernels))}
        try:
            params_cp, errs = decompose_stack(params, rank,
                                              iters=args.iters)
            params_cp = jax.tree.map(
                lambda x: jnp.asarray(x, dt), params_cp)
            row["rel_errs"] = [round(e, 4) for e in errs]
            compiled, mem = _aot_memory(
                _stack_fn(params_cp, "cp"), params_cp, sds)
            row["memory"] = mem
            obs_memory.record_program(
                "cp_fft_probe", f"{s}^4xb{b}|cp|r={rank}",
                analysis=compiled, tier="cp", source="probe")
            row["temp_vs_dense"] = vs_dense(mem)
        except Exception as e:
            row["error"] = str(e)[:300]
        report["ranks"][rank] = row

    try:
        compiled, mem = _aot_memory(_stack_fn(params, "fft"), params, sds)
        report["fft"] = {"memory": mem, "temp_vs_dense": vs_dense(mem)}
        obs_memory.record_program(
            "cp_fft_probe", f"{s}^4xb{b}|fft",
            analysis=compiled, tier="fft", source="probe")
    except Exception as e:
        report["fft"] = {"error": str(e)[:300]}

    if args.time:
        import time as _time

        import numpy as np

        def wall(p, tier):
            rng = np.random.default_rng(0)
            corr = jnp.asarray(
                rng.normal(size=(b, s, s, s, s)) * 0.05, dt)
            jitted = jax.jit(_stack_fn(p, tier))
            jax.block_until_ready(jitted(p, corr))  # compile
            walls = []
            for _ in range(args.reps):
                t0 = _time.perf_counter()
                jax.block_until_ready(jitted(p, corr))
                walls.append((_time.perf_counter() - t0) * 1e3)
            return round(float(np.median(walls)), 3)

        try:
            report["dense_wall_ms"] = wall(params, "dense")
        except Exception as e:
            _err(f"dense wall failed: {str(e)[:200]}\n")
        for rank in ranks:
            try:
                params_cp, _ = decompose_stack(params, rank,
                                               iters=args.iters)
                params_cp = jax.tree.map(
                    lambda x: jnp.asarray(x, dt), params_cp)
                report["ranks"][rank]["wall_ms"] = wall(params_cp, "cp")
            except Exception as e:
                _err(f"cp wall r={rank} failed: {str(e)[:200]}\n")
        try:
            report["fft"]["wall_ms"] = wall(params, "fft")
        except Exception as e:
            _err(f"fft wall failed: {str(e)[:200]}\n")

    _out(json.dumps(report, indent=2, sort_keys=True, default=str) + "\n")
    return 0


def tiny(args) -> int:
    """CPU smoke: parity, gate direction, and the 25⁴/k=5 AOT ledger
    acceptance row, all with no accelerator.  Exit nonzero on any
    failure — the tier-1 guard that keeps the probe runnable for the
    TPU session."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ncnet_tpu.observability import memory as obs_memory
    from ncnet_tpu.ops import conv4d, exact_cp_factors
    from ncnet_tpu.ops.conv4d_cp import (
        DEFAULT_CP_RANK,
        cp_apply_layer,
        cp_feasible,
    )
    from ncnet_tpu.ops.conv4d_fft import conv4d_fft, fft_feasible
    from ncnet_tpu.ops.cp_als import decompose_stack

    rng = np.random.default_rng(7)

    # 1) rank-full CP and FFT parity vs dense conv4d (square k=3 + small
    #    k=5 — the exhaustive shape matrix lives in the tier tests)
    for (ha, wa, hb, wb), k, c_in, c_out in (
            ((6, 6, 6, 6), 3, 2, 3), ((5, 5, 5, 5), 5, 1, 2)):
        x = jnp.asarray(
            rng.normal(size=(1, ha, wa, hb, wb, c_in)).astype(np.float32))
        w = jnp.asarray(rng.normal(
            size=(k, k, k, k, c_in, c_out)).astype(np.float32) * 0.2)
        b_ = jnp.asarray(rng.normal(size=(c_out,)).astype(np.float32))
        ref = conv4d(x, w, b_)
        d_cp = float(jnp.max(jnp.abs(
            cp_apply_layer(x, exact_cp_factors(w), b_) - ref)))
        d_fft = float(jnp.max(jnp.abs(conv4d_fft(x, w, b_) - ref)))
        _out(f"k={k} parity: rank-full CP {d_cp:.2e}, FFT {d_fft:.2e}\n")
        if d_cp > 1e-4 or d_fft > 1e-4:
            _err("FAIL: arithmetic tier parity vs dense conv4d\n")
            return 1

    # 2) gate direction at the production archs: k=5 InLoc arch clears the
    #    FFT gate at 25⁴, the k=3 arch must not; CP clears at the default
    #    rank and refuses at rank-full arithmetic
    k5, c5 = (5, 5, 5), (16, 16, 1)
    checks = (
        fft_feasible(25, 25, 25, 25, k5, c5),
        not fft_feasible(25, 25, 25, 25, (3, 3, 3), (10, 10, 1)),
        cp_feasible(25, 25, 25, 25, k5, c5, (DEFAULT_CP_RANK,) * 3),
        not cp_feasible(6, 6, 6, 6, (3,), (2,), (3 ** 4 * 2,)),
    )
    _out(f"gate direction (fft k5, !fft k3, cp r16, !cp rank-full): "
         f"{list(checks)}\n")
    if not all(checks):
        _err("FAIL: a gate verdict points the wrong way\n")
        return 1

    # 3) the acceptance row: CPU AOT memory ledger, CP at the default rank
    #    vs dense, 25⁴/k=5 stack shape (compile-only — nothing executes)
    params = _params_for(k5, c5)
    params_cp, _ = decompose_stack(params, DEFAULT_CP_RANK, iters=2)
    params_cp = jax.tree.map(jnp.asarray, params_cp)
    sds = jax.ShapeDtypeStruct((1, 25, 25, 25, 25), jnp.float32)
    cd, dense_mem = _aot_memory(_stack_fn(params, "dense"), params, sds)
    cc, cp_mem = _aot_memory(_stack_fn(params_cp, "cp"), params_cp, sds)
    if dense_mem is None or cp_mem is None:
        _out("AOT memory analysis unavailable on this backend — "
             "acceptance row skipped (fail-open)\n")
    else:
        obs_memory.record_program(
            "cp_fft_probe", "25^4xb1|dense", analysis=cd,
            tier="xla", source="probe")
        obs_memory.record_program(
            "cp_fft_probe", f"25^4xb1|cp|r={DEFAULT_CP_RANK}",
            analysis=cc, tier="cp", source="probe")
        _out(f"25^4/k=5 temp bytes: dense {dense_mem['temp_bytes']:,} "
             f"vs cp r{DEFAULT_CP_RANK} {cp_mem['temp_bytes']:,}\n")
        if cp_mem["temp_bytes"] >= dense_mem["temp_bytes"]:
            _err("FAIL: CP temp bytes not below dense at 25^4/k=5\n")
            return 1
    _out("tiny smoke: OK\n")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Per-rank/per-shape AOT + memory + wall probe of the "
                    "CP and FFT conv4d tiers vs the dense filter")
    ap.add_argument("--ranks", default="4,8,16,32",
                    help="comma-separated CP ranks to probe")
    ap.add_argument("--size", type=int, default=25,
                    help="volume side (25 = the PF-Pascal bench grid)")
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--kernels", default="5,5,5")
    ap.add_argument("--channels", default="16,16,1")
    ap.add_argument("--iters", type=int, default=20,
                    help="ALS sweeps per decomposition")
    ap.add_argument("--bf16", action="store_true", default=True)
    ap.add_argument("--no-bf16", dest="bf16", action="store_false")
    ap.add_argument("--time", action="store_true",
                    help="measure steady-state walls (TPU session)")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--tiny", action="store_true",
                    help="CPU smoke: parity/gates/AOT-ledger acceptance "
                         "(tier-1)")
    args = ap.parse_args(argv)
    if args.tiny:
        return tiny(args)
    return probe(args)


if __name__ == "__main__":
    raise SystemExit(main())
