#!/usr/bin/env python
"""Attribute the training-step backward cost stage by stage.

Times ``value_and_grad`` (w.r.t. the NC params) of ablated variants of the
weak loss, with the backbone replaced by on-device random L2-normalized
features (so the volume is BORN from the production einsum — probe hygiene)
— isolating, by differences:

  full        corr(pos+neg) → [fold/seq] filter → per-pair scores
  one_vol     positive volume only (halves the filter work)
  no_mm       mutual_matching removed before+after the NC stack
  mean_score  softmax/max score replaced by a plain volume mean
  nc_only     bare symmetric NC stack + mean (no mm, no corr pairing)

Usage: python tools/train_attr_probe.py [batch] [dtype] [fold:y/n]
"""

import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")

from _timing import timeit  # noqa: E402

from ncnet_tpu.models.ncnet import neigh_consensus  # noqa: E402
from ncnet_tpu.ops import conv4d_init, correlation_4d, mutual_matching  # noqa: E402
from ncnet_tpu.ops.norm import feature_l2_norm  # noqa: E402
from ncnet_tpu.training.loss import match_score_per_pair  # noqa: E402

B = int(sys.argv[1]) if len(sys.argv) > 1 else 8
DT = jnp.bfloat16 if (len(sys.argv) > 2 and sys.argv[2] == "bf16") else jnp.float32
FOLD = len(sys.argv) > 3 and sys.argv[3] == "y"
S, C = 25, 1024


def init_params(key):
    ks = jax.random.split(key, 3)
    chans = [(1, 16), (16, 16), (16, 1)]
    return [
        dict(zip(("w", "b"), conv4d_init(k, 5, ci, co)))
        for k, (ci, co) in zip(ks, chans)
    ]


def make_loss(variant):
    def filt(params, corr, with_mm=True):
        if with_mm:
            corr = mutual_matching(corr)
        corr = neigh_consensus(params, corr, symmetric=True)
        if with_mm:
            corr = mutual_matching(corr)
        return corr

    def loss(params, fa, fb):
        params = jax.tree.map(lambda x: x.astype(DT), params)
        corr_p = correlation_4d(fa, fb).astype(DT)
        if variant == "nc_only":
            out = neigh_consensus(params, corr_p, symmetric=True)
            return jnp.mean(out.astype(jnp.float32))
        if variant == "one_vol":
            return -jnp.mean(match_score_per_pair(filt(params, corr_p)))
        corr_n = correlation_4d(jnp.roll(fa, -1, axis=0), fb).astype(DT)
        with_mm = variant != "no_mm"
        if FOLD:
            nc = filt(params, jnp.concatenate([corr_p, corr_n], axis=0), with_mm)
            if variant == "mean_score":
                return jnp.mean(nc[B:].astype(jnp.float32)) - jnp.mean(
                    nc[:B].astype(jnp.float32))
            s = match_score_per_pair(nc)
            return jnp.mean(s[B:]) - jnp.mean(s[:B])
        nc_p = filt(params, corr_p, with_mm)
        nc_n = filt(params, corr_n, with_mm)
        if variant == "mean_score":
            return jnp.mean(nc_n.astype(jnp.float32)) - jnp.mean(
                nc_p.astype(jnp.float32))
        return jnp.mean(match_score_per_pair(nc_n)) - jnp.mean(
            match_score_per_pair(nc_p))

    return loss


def main():
    params0 = init_params(jax.random.key(7))

    for variant in ("full", "one_vol", "no_mm", "mean_score", "nc_only"):
        loss = make_loss(variant)

        def tick(carry, _loss=loss):
            fa, fb, params = carry
            val, g = jax.value_and_grad(_loss)(params, fa, fb)
            fa = fa + (val * 1e-9).astype(fa.dtype)
            params = jax.tree.map(
                lambda p, gg: p + (jnp.sum(gg.astype(jnp.float32)) * 1e-12
                                   ).astype(p.dtype), params, g)
            return (fa, fb, params)

        def make_input(key):
            k1, k2 = jax.random.split(key)
            fa = feature_l2_norm(jax.random.normal(k1, (B, S, S, C), jnp.float32))
            fb = feature_l2_norm(jax.random.normal(k2, (B, S, S, C), jnp.float32))
            return (fa, fb, params0)

        try:
            ms = timeit(tick, make_input, n_long=4, reps=3)
            print(f"{variant:12s} {ms:8.1f} ms/step  {ms / B:6.2f} ms/pair",
                  flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"{variant:12s} FAILED: {str(e)[:200]}", flush=True)


if __name__ == "__main__":
    main()
