#!/usr/bin/env python
"""Composed-filter timings for per-layer variant combos, incl. 'abfold'.

The r5 stage breakdown (filter_stage_probe.py) pinned the composed cost:
L2 (16->16) 4.56 ms/pair at 28% MXU, L3 (16->1) 2.12 at 3.7% — the rest is
noise.  This probe times the FULL composed filter (corr -> mm -> batch-fold
-> L1 -> L2 -> L3 -> unfold -> mm) with per-layer variant overrides, plus a
new 'abfold' formulation: kA folded into INPUT channels (shift-concat) and
kWA folded into OUTPUT channels (shifted sum), turning the 4D conv into a
single 2D conv over (hB, wB) with kA*C_in x kWA*C_out channels — an
80x80-channel (5,5) conv for L2, the shape class XLA's TPU conv lowering
handles best (ResNet-like), instead of coutfold's 3D conv with its
kA-shifted channel-slice epilogue.

Usage: python tools/filter_combo_probe.py [batch]
"""

import sys

import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, "/root/repo")

from _timing import timeit  # noqa: E402

B = int(sys.argv[1]) if len(sys.argv) > 1 else 4
S = 25
DT = jnp.bfloat16


def conv4d_abfold(x, weight, bias=None):
    """kA -> input-channel fold, kWA -> output-channel fold; one 2D conv."""
    b, ha, wa, hb, wb, c_in = x.shape
    ka, kwa, kb, kwb, _, c_out = weight.shape
    xp = jnp.pad(x, ((0, 0), (ka // 2, ka // 2)) + ((0, 0),) * 4)
    shifts = jnp.concatenate(
        [lax.slice_in_dim(xp, p, p + ha, axis=1) for p in range(ka)], axis=-1
    )  # (b, ha, wa, hb, wb, ka*c_in)
    # kernel (kb, kwb, ka*c_in, kwa*c_out): w[p,q,r,s,c,o] -> [(r,s),(p,c),(q,o)]
    wf = jnp.transpose(weight, (2, 3, 0, 4, 1, 5)).reshape(
        kb, kwb, ka * c_in, kwa * c_out
    )
    dn = lax.conv_dimension_numbers(
        (b * ha * wa, hb, wb, ka * c_in), wf.shape, ("NHWC", "HWIO", "NHWC")
    )
    y = lax.conv_general_dilated(
        shifts.reshape(b * ha * wa, hb, wb, ka * c_in),
        wf,
        window_strides=(1, 1),
        padding=[(kb // 2, kb // 2), (kwb // 2, kwb // 2)],
        dimension_numbers=dn,
    )
    y = y.reshape(b, ha, wa, hb, wb, kwa * c_out)
    y = jnp.pad(y, ((0, 0), (0, 0), (kwa // 2, kwa // 2)) + ((0, 0),) * 3)
    out = None
    for q in range(kwa):
        o = lax.slice_in_dim(y, q, q + wa, axis=2)[..., q * c_out:(q + 1) * c_out]
        out = o if out is None else out + o
    if bias is not None:
        out = out + bias
    return out


def make_input(key):
    k1, k2, *ks = jax.random.split(key, 5)
    fa = jax.random.normal(k1, (B, S, S, 128), jnp.float32) * 0.03
    fb = jax.random.normal(k2, (B, S, S, 128), jnp.float32) * 0.03
    chans = [(1, 16), (16, 16), (16, 1)]
    params = []
    for kk, (ci, co) in zip(ks, chans):
        params.append({
            "w": jax.random.normal(kk, (5, 5, 5, 5, ci, co), DT) * 0.05,
            "b": jnp.zeros((co,), DT),
        })
    return fa, fb, params


def make_step(variants):
    from ncnet_tpu.ops import correlation_4d, mutual_matching
    from ncnet_tpu.ops.conv4d import conv4d

    def apply(i, x, params):
        v = variants[i]
        w, bias = params[i]["w"], params[i]["b"]
        if v == "abfold":
            return jax.nn.relu(conv4d_abfold(x, w, bias))
        return jax.nn.relu(conv4d(x, w, bias, variant=v))

    def step(carry):
        fa, fb, params = carry
        x = correlation_4d(fa.astype(DT), fb.astype(DT))
        x = mutual_matching(x)[..., None]
        xt = jnp.transpose(x, (0, 3, 4, 1, 2, 5))
        x = jnp.concatenate([x, xt], axis=0)
        for i in range(3):
            x = apply(i, x, params)
        y = x[..., 0]
        x = mutual_matching(y[:B] + jnp.transpose(y[B:], (0, 3, 4, 1, 2)))
        eps = (jnp.sum(x.astype(jnp.float32)) * 1e-12).astype(fa.dtype)
        return fa + eps, fb, params

    return step


def check_abfold():
    """Numerical parity of abfold vs the production conv4d."""
    import numpy as np

    from ncnet_tpu.ops.conv4d import conv4d

    key = jax.random.key(0)
    x = jax.random.normal(key, (2, 7, 7, 7, 7, 16), jnp.float32)
    w = jax.random.normal(jax.random.key(1), (5, 5, 5, 5, 16, 8), jnp.float32)
    b = jax.random.normal(jax.random.key(2), (8,), jnp.float32)
    ref = conv4d(x, w, b, variant="unroll")
    got = conv4d_abfold(x, w, b)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4,
                               atol=2e-4)
    print("abfold parity OK")


COMBOS = [
    ("baseline (tapfold,coutfold,coutfold)", ["tapfold", "coutfold", "coutfold"]),
    ("L2=abfold", ["tapfold", "abfold", "coutfold"]),
    ("L3=afold", ["tapfold", "coutfold", "afold"]),
    ("L2=abfold L3=afold", ["tapfold", "abfold", "afold"]),
    ("L1=abfold L2=abfold L3=afold", ["abfold", "abfold", "afold"]),
    ("L2=abfold L3=abfold", ["tapfold", "abfold", "abfold"]),
]


def main():
    check_abfold()
    print(f"device={jax.devices()[0].device_kind} batch={B} dtype=bf16")
    for name, variants in COMBOS:
        try:
            ms = timeit(make_step(variants), make_input, per=B, n_long=8)
            print(f"{name:>36}: {ms:7.3f} ms/pair")
        except Exception as e:
            print(f"{name:>36}: ERR {str(e)[:80]}")


if __name__ == "__main__":
    main()
