#!/usr/bin/env python
"""Resumable bulk builder for the persistent database feature store.

The InLoc database is fixed; its backbone features should be computed ONCE,
offline, instead of lazily during the first (cold) serving day.  This tool
walks a densePE shortlist's unique pano set and resolves every image
through the same ``matcher.prepare_db`` path the eval/serving tiers read —
so the committed bytes are bit-identical to what a live miss would compute,
and a later ``run_inloc_eval --feature_store_dir`` (or the serving engine's
store path) starts 100% warm.

Robustness (the PR 3 discipline, reused wholesale):

  * each pano builds under ``run_isolated`` — bounded retry + backoff,
    classified failures, tier demote-retrace on device errors — and an
    exhausted budget QUARANTINES the pano into a per-shard manifest
    (``<store_dir>/build_manifest.shard<i>_of_<n>.json``) instead of
    aborting the multi-hour build;
  * resumable two ways: a completed unit in the manifest is skipped without
    even decoding, and a unit whose entry already sits in the store is a
    verified hit (so a SIGKILLed build rerun fast-forwards — the store's
    two-phase commits guarantee no torn entry can fool it);
  * striping: ``--shard_index/--shard_count`` split the pano set across
    hosts, one manifest per stripe (concurrent hosts share the store root;
    entry commits are atomic and content-addressed, so double-building an
    overlapping pano is harmless, not corrupting).

Exit codes: 0 = every pano in this stripe built (or already present),
2 = quarantined panos remain (see the manifest).

Usage::

    python tools/build_feature_store.py --store_dir /data/fstore \
        --inloc_shortlist datasets/inloc/densePE_top100_shortlist_cvpr18.mat \
        --pano_path datasets/inloc/pano/ --checkpoint <ckpt> \
        [--image_size 3200] [--k_size 2] [--n_panos 10] [--budget_mb 0] \
        [--shard_index 0 --shard_count 4]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="Bulk-build the persistent database feature store from "
                    "an InLoc shortlist (resumable, per-shard manifests)")
    p.add_argument("--store_dir", required=True,
                   help="feature store root (shared across shards)")
    p.add_argument("--inloc_shortlist", type=str,
                   default="datasets/inloc/densePE_top100_shortlist_cvpr18"
                           ".mat")
    p.add_argument("--pano_path", type=str, default="datasets/inloc/pano/")
    p.add_argument("--checkpoint", type=str, default="")
    p.add_argument("--backbone", type=str, default="",
                   help="override the trunk when building without a "
                        "checkpoint (e.g. 'tiny' for the CPU smoke test); "
                        "default: the ModelConfig default")
    p.add_argument("--image_size", type=int, default=3200)
    p.add_argument("--k_size", type=int, default=2)
    p.add_argument("--n_panos", type=int, default=10,
                   help="shortlist depth per query (the eval's n_panos — "
                        "only these panos are ever read)")
    p.add_argument("--budget_mb", type=int, default=0,
                   help="LRU eviction budget in MiB (0 = unbounded; a bulk "
                        "build larger than the budget churns — size it)")
    p.add_argument("--shard_index", type=int, default=0)
    p.add_argument("--shard_count", type=int, default=1)
    p.add_argument("--retries", type=int, default=2)
    p.add_argument("--retry_backoff_s", type=float, default=0.5)
    p.add_argument("--no_gc", action="store_true",
                   help="skip superseded-generation GC on open")
    p.add_argument("--telemetry_dir", type=str, default="",
                   help="open a structured event log here (store events + "
                        "retry/quarantine; replay with run_report --store)")
    return p


def unique_panos(shortlist_path: str, n_panos: int):
    """The de-duplicated pano name list a depth-``n_panos`` eval would ever
    read, in first-appearance order (deterministic across shards)."""
    from ncnet_tpu.evaluation.inloc import _as_str, load_shortlist

    _, pano_fns = load_shortlist(shortlist_path)
    seen, out = set(), []
    for fns in pano_fns:
        for idx in range(min(n_panos, len(fns))):
            name = _as_str(fns[idx])
            if name not in seen:
                seen.add(name)
                out.append(name)
    return out


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    out = sys.stdout.write

    if not 0 <= args.shard_index < max(1, args.shard_count):
        raise SystemExit(f"shard_index {args.shard_index} out of range for "
                         f"shard_count {args.shard_count}")

    # deferred imports: --help must not pay jax startup
    import jax

    from ncnet_tpu.config import ModelConfig
    from ncnet_tpu.data.datasets import load_image
    from ncnet_tpu.evaluation.inloc import make_pair_matcher
    from ncnet_tpu.evaluation.resilience import (
        FaultPolicy,
        RunManifest,
        run_isolated,
    )
    from ncnet_tpu.models.ncnet import recover_from_device_failure
    from ncnet_tpu.observability import events as obs_events
    from ncnet_tpu.store import FeatureStore, backbone_fingerprint

    base = ModelConfig(checkpoint=args.checkpoint, half_precision=True,
                       relocalization_k_size=args.k_size,
                       **({"backbone": args.backbone} if args.backbone
                          else {}))
    if args.checkpoint:
        from ncnet_tpu.models.checkpoint import load_params

        model_config, params = load_params(args.checkpoint, base)
        model_config = model_config.replace(
            half_precision=True, relocalization_k_size=args.k_size)
    else:
        from ncnet_tpu.models.ncnet import init_ncnet

        model_config, params = base, None
        params = init_ncnet(model_config, jax.random.key(1))

    own_sink = None
    if args.telemetry_dir:
        from ncnet_tpu.observability.events import EventLog

        log_name = ("events.jsonl" if args.shard_count <= 1 else
                    f"events.shard{args.shard_index}.jsonl")
        own_sink = EventLog(
            os.path.join(args.telemetry_dir, log_name),
            run_meta={"tool": "build_feature_store",
                      "shard_index": args.shard_index,
                      "shard_count": args.shard_count})
        obs_events.set_global_sink(own_sink)

    fingerprint = backbone_fingerprint(
        params, image_size=args.image_size, k_size=args.k_size, dtype="bf16")
    store = FeatureStore(args.store_dir, fingerprint,
                         budget_bytes=args.budget_mb * 2 ** 20,
                         scope="store_build")
    if not args.no_gc:
        store.gc_superseded()
    matcher = make_pair_matcher(
        model_config, params, do_softmax=True, both_directions=True,
        flip_direction=False, preprocess_image_size=args.image_size,
        store=store)

    panos = unique_panos(args.inloc_shortlist, args.n_panos)
    stripe = panos[args.shard_index::max(1, args.shard_count)]
    manifest = RunManifest(
        os.path.join(
            args.store_dir,
            f"build_manifest.shard{args.shard_index}"
            f"_of_{max(1, args.shard_count)}.json"),
        meta={"tool": "build_feature_store", "fingerprint": fingerprint,
              "shortlist": os.path.basename(args.inloc_shortlist),
              "n_panos": args.n_panos,
              "shard_index": args.shard_index,
              "shard_count": max(1, args.shard_count)})
    policy = FaultPolicy(retries=args.retries,
                         backoff_s=args.retry_backoff_s, quarantine=True)

    t0 = time.perf_counter()
    built = skipped = 0
    statuses = {"hit": 0, "miss": 0, "recompute": 0}
    for name in stripe:
        if manifest.is_completed(name):
            skipped += 1
            continue

        def work(name=name):
            raw = load_image(os.path.join(args.pano_path, name))[None]
            return matcher.prepare_db(raw)

        def on_failure(exc, kind):
            if kind == "device":
                return recover_from_device_failure(exc, matcher)
            return None

        ok, prepared = run_isolated(name, work, policy=policy,
                                    manifest=manifest,
                                    on_failure=on_failure,
                                    label=f"pano {name}")
        if ok:
            built += 1
            statuses[prepared.status] = statuses.get(prepared.status, 0) + 1

    doc = {
        "tool": "build_feature_store",
        "fingerprint": fingerprint,
        "shard": f"{args.shard_index}/{max(1, args.shard_count)}",
        "stripe_panos": len(stripe),
        "built": built,
        "skipped_completed": skipped,
        "statuses": statuses,
        "quarantined": list(manifest.quarantined_ids),
        "store": store.flush_stats(tool="build_feature_store"),
        "wall_s": round(time.perf_counter() - t0, 3),
    }
    out(json.dumps(doc, sort_keys=True) + "\n")
    store.close()
    if own_sink is not None:
        obs_events.set_global_sink(None)
        own_sink.close()
    return 2 if manifest.quarantined_ids else 0


if __name__ == "__main__":
    raise SystemExit(main())
