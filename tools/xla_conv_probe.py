#!/usr/bin/env python
"""Where does coutfold's 3.5 ms/pair on the 16->16 NC layer actually go?

Times (scan-differenced, like bench.py) a ladder of programs that bracket the
layer from below:

  1. gemm_coutfold : the bare GEMM XLA's conv should reduce to
                     (M=b*25^4, K=5^3*16=2000, N=5*16=80)
  2. gemm_square   : same FLOPs, square-ish shape (K=400, N=400) — what an
                     explicit rs-im2col/pq-outfold formulation would run
  3. conv_coutfold : the current production formulation (conv4d coutfold)
  4. conv_unroll / conv_afold / conv_tapfold: the other formulations
  5. im2col_gemm   : explicit B-side im2col + square GEMM + pq shifted-sum
                     (the afold dataflow with the GEMM exposed to XLA)

If (1) ~= (3), the GEMM shape is the wall and only a Pallas N-fold helps;
if (1) << (3), XLA's conv lowering is the wall and an XLA-level rewrite wins.

Usage: python tools/xla_conv_probe.py [batch]
"""

import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")

from _timing import timeit  # noqa: E402

B = int(sys.argv[1]) if len(sys.argv) > 1 else 4
S = 25            # PF-Pascal grid
K5 = 5            # kernel size
C = 16            # channels
DT = jnp.bfloat16




def chain(op):
    def step(carry):
        x, w = carry
        out = op(x, w)
        eps = (jnp.sum(out.astype(jnp.float32)) * 1e-12).astype(x.dtype)
        return x + eps, w - eps
    return step


def vol_input(key):
    k1, k2 = jax.random.split(key)
    x = jax.random.normal(k1, (B, S, S, S, S, C), DT) * 0.03
    w = jax.random.normal(k2, (K5,) * 4 + (C, C), DT) * 0.05
    return x, w


def gemm_input(m, k, n):
    def make(key):
        k1, k2 = jax.random.split(key)
        return (
            jax.random.normal(k1, (m, k), DT) * 0.03,
            jax.random.normal(k2, (k, n), DT) * 0.05,
        )
    return make


def main():
    print(f"device={jax.devices()[0].device_kind} batch={B} dtype={DT.__name__}")
    res = {}

    m = B * S ** 4
    res["gemm_coutfold_MK2000N80"] = timeit(
        chain(lambda a, w: jnp.dot(a, w, preferred_element_type=jnp.float32)
              .astype(DT)),
        gemm_input(m, 2000, 80), per=B, n_long=8,
    )
    res["gemm_square_MK400N400"] = timeit(
        chain(lambda a, w: jnp.dot(a, w, preferred_element_type=jnp.float32)
              .astype(DT)),
        gemm_input(m, 400, 400), per=B, n_long=8,
    )

    from ncnet_tpu.ops.conv4d import conv4d

    for variant in ("coutfold", "unroll", "tapfold", "afold"):
        res[f"conv_{variant}"] = timeit(
            chain(lambda x, w, v=variant: conv4d(x, w, variant=v)),
            vol_input, per=B, n_long=8,
        )

    def im2col_gemm(x, w):
        # B-side (r,s,c) im2col -> square GEMM -> (p,q) shifted-sum epilogue
        b, ha, wa, hb, wb, c = x.shape
        k = w.shape[0]
        pad = k // 2
        xp = jnp.pad(x, ((0, 0), (0, 0), (0, 0), (pad, pad), (pad, pad), (0, 0)))
        patches = jnp.concatenate(
            [
                xp[:, :, :, r : r + hb, s : s + wb, :]
                for r in range(k)
                for s in range(k)
            ],
            axis=-1,
        )  # (b, ha, wa, hb, wb, k*k*c)
        w2 = jnp.transpose(w, (2, 3, 4, 0, 1, 5)).reshape(k * k * c, k * k * c)
        y = jnp.einsum("bjqklr,rn->bjqkln", patches, w2)  # n = (p,q,o)
        y = jnp.pad(y, ((0, 0), (pad, pad), (pad, pad), (0, 0), (0, 0), (0, 0)))
        out = None
        for p in range(k):
            for q in range(k):
                t = (p * k + q) * c
                o = y[:, p : p + ha, q : q + wa, :, :, t : t + c]
                out = o if out is None else out + o
        return out

    res["im2col_gemm"] = timeit(chain(im2col_gemm), vol_input, per=B, n_long=8)

    for k, v in sorted(res.items()):
        print(f"{k:>28}: {v:7.3f} ms/pair")


if __name__ == "__main__":
    main()
