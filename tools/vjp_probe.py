#!/usr/bin/env python
"""Pick conv4d_same's weight-gradient formulation by measurement.

Times value_and_grad through the production symmetric NC stack (the training
hot path) for each dw-variant choice, plus the plain-AD baseline, and prints
XLA's peak-memory estimate — the bs8 fp32 step must FIT 16G (plain AD does;
dw='unroll' blew it to 20.9G via channel-minor relayouts).

Usage: python tools/vjp_probe.py [batch] [dtype]
"""

import importlib
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")

from _timing import timeit  # noqa: E402

B = int(sys.argv[1]) if len(sys.argv) > 1 else 8
DT = jnp.bfloat16 if (len(sys.argv) > 2 and sys.argv[2] == "bf16") else jnp.float32
S = 25

c4mod = importlib.import_module("ncnet_tpu.ops.conv4d")
ncmod = importlib.import_module("ncnet_tpu.models.ncnet")




def stack_input(key):
    k1, *ks = jax.random.split(key, 4)
    corr = jax.random.normal(k1, (B, S, S, S, S), DT) * 0.03
    chans = [(1, 16), (16, 16), (16, 1)]
    params = []
    for kk, (ci, co) in zip(ks, chans):
        params.append({
            "w": jax.random.normal(kk, (5, 5, 5, 5, ci, co), DT) * 0.05,
            "b": jnp.zeros((co,), DT),
        })
    return corr, params


CUSTOM_GRAD = True  # main() clears this for the plain-AD baseline row


def grad_step(carry):
    corr, params = carry

    def loss(params, corr):
        out = ncmod.neigh_consensus(
            params, corr, symmetric=True, custom_grad=CUSTOM_GRAD
        )
        return jnp.mean(jax.nn.softmax(
            out.reshape(out.shape[0], -1).astype(jnp.float32), axis=-1
        ).max(axis=-1))

    l, g = jax.value_and_grad(loss, argnums=(0, 1))(params, corr)
    gp, gc = g
    eps = (l * 1e-9).astype(corr.dtype)
    new_params = jax.tree.map(
        lambda p, gg: p + (jnp.sum(gg.astype(jnp.float32)) * 1e-12).astype(p.dtype),
        params, gp,
    )
    return corr + eps + gc.astype(corr.dtype) * 1e-12, new_params


def peak_mem_gb():
    @jax.jit
    def one(carry):
        return grad_step(carry)

    import numpy as np
    rng = np.random.default_rng(0)
    corr, params = jax.eval_shape(lambda k: stack_input(k), jax.random.key(0)), None
    c = jax.ShapeDtypeStruct((B, S, S, S, S), DT)
    chans = [(1, 16), (16, 16), (16, 1)]
    ps = [
        {"w": jax.ShapeDtypeStruct((5, 5, 5, 5, ci, co), DT),
         "b": jax.ShapeDtypeStruct((co,), DT)}
        for ci, co in chans
    ]
    try:
        mem = one.lower((c, ps)).compile().memory_analysis()
        return mem.temp_size_in_bytes / 2**30
    except Exception as e:
        return float("nan") if "memory" not in str(e).lower() else -1.0


def main():
    print(f"device={jax.devices()[0].device_kind} batch={B} "
          f"dtype={jnp.dtype(DT).name}")
    configs = [("plain_ad", None), ("dw_coutfold", "coutfold"),
               ("dw_tapfold", "tapfold"), ("dw_afold", "afold"),
               ("dw_unroll", "unroll")]
    global CUSTOM_GRAD
    for name, dwv in configs:
        # plain_ad row: custom_grad off → XLA transposes the forward itself
        CUSTOM_GRAD = dwv is not None
        if dwv is not None:
            c4mod._DW_VARIANT = dwv
        try:
            mem = peak_mem_gb()
            ms = timeit(grad_step, stack_input, n_long=4, per=B)
            print(f"{name:>12}: {ms:7.3f} ms/pair   temp {mem:5.1f} GB")
        except Exception as e:
            print(f"{name:>12}: ERR {str(e)[:120]}")


if __name__ == "__main__":
    main()
