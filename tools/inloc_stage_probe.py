#!/usr/bin/env python
"""Stage-isolated timings of the InLoc-resolution matcher on device.

The round-2 cumulative-prefix breakdown proved unreliable (it charged
warmup/upload to the first stage: "preprocess 119 ms" vs 4 ms measured in
isolation).  This probe times each stage standalone with the scan-differenced
harness at the real InLoc db shapes (query 4032x3024 / db 1200x1600 resized
to max side 3200, k=2, IVD arch 1->16/16->1 k3, bf16), so the per-pair device
total can be attributed and attacked.

Probe hygiene (see tools/_timing.py): volumes are born from a correlation
einsum (raw random volumes trigger pathological maxpool4d layouts), and the
carry consumes the coordinate/delta outputs too, so relocalization work is
not dead-code-eliminated out of the timings.

Usage: python tools/inloc_stage_probe.py
"""

import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")

from _timing import timeit  # noqa: E402

from ncnet_tpu.config import ModelConfig  # noqa: E402
from ncnet_tpu import models  # noqa: E402
from ncnet_tpu.evaluation.inloc import quantized_resize_shape  # noqa: E402
from ncnet_tpu.models.ncnet import extract_features, ncnet_filter  # noqa: E402
from ncnet_tpu.ops import corr_to_matches, correlation_4d  # noqa: E402
from ncnet_tpu.ops.image import (  # noqa: E402
    normalize_imagenet,
    resize_bilinear_align_corners,
)

CFG = ModelConfig(
    ncons_kernel_sizes=(3, 3), ncons_channels=(16, 1),
    half_precision=True, backbone_bf16=True, relocalization_k_size=2,
)

# query 4032x3024 portrait and db 1200x1600, both resized to max side 3200
# with k*16 quantization — the real eval shapes
QH, QW = quantized_resize_shape(4032, 3024, 3200, 2)    # (3200, 2400)
DH, DW = quantized_resize_shape(1200, 1600, 3200, 2)    # (2400, 3200)
FQ = (QH // 16, QW // 16)   # fine feature grids
FD = (DH // 16, DW // 16)
PQ = (FQ[0] // 2, FQ[1] // 2)  # pooled
PD = (FD[0] // 2, FD[1] // 2)


def chain1(op):
    def step(x):
        out = op(x)
        eps = (jnp.sum(jax.tree.leaves(out)[0].astype(jnp.float32)) * 1e-12)
        return x + eps.astype(x.dtype)
    return step


def main():
    import warnings

    print(f"device={jax.devices()[0].device_kind}  "
          f"query {QH}x{QW} -> fine {FQ} pooled {PQ}; "
          f"db {DH}x{DW} -> fine {FD} pooled {PD}")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        params = models.init_ncnet(CFG, jax.random.key(0))
    res = {}

    # 1. db preprocess (uint8 -> normalize -> quantized resize)
    def prep_in(key):
        return jax.random.randint(key, (1, 1200, 1600, 3), 0, 255, jnp.uint8)

    def prep(img):
        x = normalize_imagenet(img.astype(jnp.float32))
        out = resize_bilinear_align_corners(x, DH, DW)
        return img + (jnp.sum(out) * 1e-12).astype(jnp.uint8)

    res["preprocess_db"] = timeit(prep, prep_in)

    # 2. backbone on the db image (the per-pair trunk; the query's is
    # amortized over ~10 pairs)
    def bb_in(key):
        return jax.random.uniform(key, (1, DH, DW, 3), jnp.float32, -1, 1)

    res["backbone_db"] = timeit(
        chain1(lambda x: extract_features(CFG, params, x)), bb_in
    )

    # 3. fine correlation (bf16 features)
    def corr_in(key):
        k1, k2 = jax.random.split(key)
        return (
            jax.random.normal(k1, (1, *FQ, 1024), jnp.bfloat16) * 0.03,
            jax.random.normal(k2, (1, *FD, 1024), jnp.bfloat16) * 0.03,
        )

    def corr_step(carry):
        a, b = carry
        out = correlation_4d(a, b)
        eps = (jnp.sum(out.astype(jnp.float32)) * 1e-12).astype(a.dtype)
        return a + eps, b

    res["correlation_fine"] = timeit(corr_step, corr_in)

    # 4. filter: maxpool4d(k=2) + mutual + NC + mutual on the fine volume.
    # Born from a correlation einsum (8 anchor channels, ~0.5 ms) and the
    # carry consumes BOTH the filtered volume and the delta4d offsets so the
    # argmax bookkeeping is measured, not DCE'd.
    def vol_in(key):
        k1, k2 = jax.random.split(key)
        return (
            jax.random.normal(k1, (1, *FQ, 8), jnp.bfloat16) * 0.2,
            jax.random.normal(k2, (1, *FD, 8), jnp.bfloat16) * 0.2,
        )

    def filter_step(carry):
        fa, fb = carry
        out = ncnet_filter(CFG, params, correlation_4d(fa, fb))
        eps = jnp.sum(out.corr.astype(jnp.float32)) * 1e-12
        for d in out.delta4d:
            eps = eps + jnp.sum(d.astype(jnp.float32)) * 1e-12
        return fa + eps.astype(fa.dtype), fb

    res["filter_pool_mm_nc"] = timeit(filter_step, vol_in)

    # 5. match extraction, both directions, softmax, on the pooled volume —
    # every output column consumed so the relocalization gathers survive DCE
    def pooled_in(key):
        k1, k2 = jax.random.split(key)
        corr = jax.random.normal(k1, (1, *PQ, *PD), jnp.float32) * 0.03
        delta = tuple(
            jax.random.randint(k2, (1, *PQ, *PD), 0, 2, jnp.int32)
            for _ in range(4)
        )
        return corr, delta

    def extract_step(carry):
        corr, delta = carry
        eps = 0.0
        for inv in (False, True):
            m = corr_to_matches(corr, delta4d=delta, k_size=2,
                                do_softmax=True, scale="positive",
                                invert_matching_direction=inv)
            eps = eps + sum(
                jnp.sum(v) for v in (m.xA, m.yA, m.xB, m.yB, m.score)
            ) * 1e-12
        return corr + eps.astype(corr.dtype), delta

    res["extract_both_dirs"] = timeit(extract_step, pooled_in)

    total = sum(res.values())
    for k, v in res.items():
        print(f"{k:>20}: {v:7.1f} ms")
    print(f"{'sum of stages':>20}: {total:7.1f} ms")


if __name__ == "__main__":
    main()
