#!/usr/bin/env python
"""Perf regression sentinel over the persistent perf store.

The perf store (``ncnet_tpu/observability/perfstore.py``) accumulates every
bench/fit/eval metric as an append-only JSONL history keyed by
``(device_kind, metric, git rev)``.  This tool is the gate on top:

  * ``--seed``: (re)build a store from BENCH_r*.json artifacts — the repo's
    committed history at ``perf/history.jsonl`` is produced this way, so
    the trajectory those loose files encode becomes something a CI job can
    gate on.  Seeding an existing store appends; pass a fresh ``--store``
    to rebuild from scratch.
  * ``--check``: judge the NEWEST value of each gated ``(device_kind,
    metric)`` series against its trailing baseline window with the
    median + MAD threshold (``check_regressions``) and **exit 1 on any
    regression** — wire it after a bench/fit run and a >threshold step-wall
    jump fails the job.  Metrics whose direction cannot be inferred from
    their name (MFU, TFLOP/s, vs_baseline, roofline constants) are
    report-only; ``--metrics`` focuses (and force-gates) an explicit list.

Usage::

    python tools/perf_regress.py --seed BENCH_r*.json [--store perf/history.jsonl]
    python tools/perf_regress.py --check [--store ...] [--device-kind ...]
        [--window 8] [--mad-k 4.0] [--min-rel 0.10] [--metrics a,b,...] [--json]

Exit codes: 0 = no regression (or seed OK), 1 = regression detected,
2 = usage/store error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ncnet_tpu.observability.perfstore import (  # noqa: E402
    PerfStore,
    check_regressions,
    ingest_bench_artifact,
    resolve_store_path,
)


def _render(findings: List[dict]) -> str:
    lines: List[str] = []
    n_reg = sum(1 for f in findings if f["status"] == "regression")
    n_ok = sum(1 for f in findings if f["status"] == "ok")
    n_skip = sum(1 for f in findings if f["status"] == "skipped")
    lines.append(f"=== perf_regress: {n_reg} regression(s), {n_ok} ok, "
                 f"{n_skip} skipped ===")
    for f in findings:
        tag = {"regression": "REGRESSION", "ok": "ok",
               "skipped": "skipped"}[f["status"]]
        line = (f"[{tag}] {f['metric']} ({f['device_kind']}, "
                f"{f['direction']}-is-better): value={f['value']:.6g}")
        if f["status"] == "skipped":
            line += f"  ({f['reason']})"
        else:
            line += (f"  baseline median={f['baseline_median']:.6g} "
                     f"mad={f['baseline_mad']:.6g} slack={f['slack']:.6g} "
                     f"worse_by={f['worse_by']:.6g} "
                     f"n_history={f['n_history']}")
        lines.append(line)
    if not findings:
        lines.append("(no gated series in the store)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Seed and gate the persistent perf history")
    ap.add_argument("--store", default=None,
                    help="perf store path (default: $NCNET_TPU_PERF_STORE "
                         "or <repo>/perf/history.jsonl)")
    ap.add_argument("--seed", nargs="+", metavar="BENCH.json", default=None,
                    help="ingest bench artifact file(s) into the store")
    ap.add_argument("--check", action="store_true",
                    help="judge newest values against the trailing baseline; "
                         "exit 1 on regression")
    ap.add_argument("--window", type=int, default=8,
                    help="trailing baseline window size (default 8)")
    ap.add_argument("--mad-k", type=float, default=4.0,
                    help="MAD multiplier (sigma-scaled) for the noise "
                         "threshold (default 4.0)")
    ap.add_argument("--min-rel", type=float, default=0.10,
                    help="relative slack floor vs the baseline median "
                         "(default 0.10)")
    ap.add_argument("--min-history", type=int, default=2,
                    help="baseline points required before gating a series "
                         "(default 2)")
    ap.add_argument("--metrics", default=None,
                    help="comma-separated metric names to check (forces "
                         "gating even for report-only names)")
    ap.add_argument("--device-kind", default=None,
                    help="restrict the check to one device kind")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as one JSON document")
    args = ap.parse_args(argv)

    store_path = resolve_store_path(args.store)
    if store_path is None:
        sys.stderr.write("perf_regress: store disabled "
                         "(NCNET_TPU_PERF_STORE=off) and no --store given\n")
        return 2
    store = PerfStore(store_path)

    if args.seed:
        total = 0
        for path in args.seed:
            try:
                n = ingest_bench_artifact(store, path)
            except (OSError, ValueError) as e:
                sys.stderr.write(f"perf_regress: cannot ingest {path}: "
                                 f"{e}\n")
                return 2
            sys.stderr.write(f"seeded {n} record(s) from {path}\n")
            total += n
        sys.stderr.write(f"store {store_path}: +{total} record(s), "
                         f"{len(store.records())} total\n")
        if not args.check:
            return 0

    if not args.check and not args.seed:
        sys.stderr.write("perf_regress: nothing to do (pass --seed and/or "
                         "--check)\n")
        return 2

    records = store.records()
    if not records:
        sys.stderr.write(f"perf_regress: store {store_path} is missing or "
                         "empty\n")
        return 2
    metrics = ([m.strip() for m in args.metrics.split(",") if m.strip()]
               if args.metrics else None)
    findings = check_regressions(
        records, window=args.window, mad_k=args.mad_k,
        min_rel=args.min_rel, min_history=args.min_history,
        metrics=metrics, device_kind=args.device_kind,
    )
    if args.json:
        print(json.dumps({"store": store_path, "findings": findings},
                         indent=2, sort_keys=True))
    else:
        print(_render(findings))
    return 1 if any(f["status"] == "regression" for f in findings) else 0


if __name__ == "__main__":
    raise SystemExit(main())
