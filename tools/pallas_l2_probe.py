#!/usr/bin/env python
"""Measure a fused-(hB*wB)-lane Pallas kernel for the L2 conv (16->16, 5^4).

The r5 composed breakdown (filter_stage_probe.py): L2 = 4.56 ms/pair at bs4
(= 2.28 ms/volume at the batch-folded 2B=8) — 28% of MXU peak — and every
XLA-level reformulation measured worse (filter_combo_probe.py).  This kernel
tests the one shape XLA cannot express: volume tiles of layout
``(j, C sublanes, fused padded (hB+4)(wB+4)=841 lanes)`` where

  * K = (kA, kWA, C_in) = 400 fills the MXU contraction depth (vs XLA conv
    lowering's effective 28%),
  * the B-side (kB, kWB) taps become PURE LANE OFFSETS of the fused kl dim
    (r*29+s), resolved by a vectorized VMEM epilogue over N=(r,s,o)=400,
  * inter-op intermediates never touch HBM.

All primitives probed legal on this toolchain (tools/mosaic_probes.py
r5_*).  Prints ms/volume for the kernel (including the XLA-side layout
conversion, measured separately) vs the XLA composed reference.

Usage: python tools/pallas_l2_probe.py [batch]
"""

import functools
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, "/root/repo")

from _timing import timeit  # noqa: E402

B = int(sys.argv[1]) if len(sys.argv) > 1 else 8
S, K, C = 25, 5, 16
SP = S + K - 1          # 29: padded rows/cols
KL = SP * SP            # 841 fused (k,l) lanes
JCH = int(sys.argv[2]) if len(sys.argv) > 2 else 5   # j-chunk
YDT = jnp.bfloat16 if (len(sys.argv) <= 3 or sys.argv[3] == "bf16") \
    else jnp.float32  # Ybuf dtype between dot and epilogue
# ablation: full | noepi (build+dots, sum Y) | nodots (build only, sum A3)
MODE = sys.argv[4] if len(sys.argv) > 4 else "full"
# A-build method: concat (one 25-piece sublane concat) | scratch (stores)
BUILD = sys.argv[5] if len(sys.argv) > 5 else "concat"


def _kernel(*refs, je_list):
    """One (b, i) step: refs = (x_0..x_4, w, bias, mask, out[, a_scr])."""
    x_refs, w_ref, b_ref, m_ref, out_ref = refs[:K], refs[K], refs[K + 1], \
        refs[K + 2], refs[K + 3]
    a_scr = refs[K + 4] if BUILD == "scratch" else None
    w = w_ref[:]
    for j0, je in je_list:
        # A3[(j), (p,q,c), (kl)]: 25 shifted row slabs along the sublane dim
        if BUILD == "scratch":
            for p in range(K):
                for q in range(K):
                    pq = p * K + q
                    a_scr[:je, pq * C:(pq + 1) * C, :] = \
                        x_refs[p][0, 0, j0 + q:j0 + q + je]
            a3 = a_scr[:je]
        else:
            a3 = jnp.concatenate(
                [x_refs[p][0, 0, j0 + q:j0 + q + je] for p in range(K)
                 for q in range(K)],
                axis=1,
            )  # (je, 400, 841)
        if MODE == "nodots":
            out_ref[0, 0, j0:j0 + je] = jnp.broadcast_to(
                jnp.sum(a3.astype(jnp.float32)) * 1e-9, (je, C, KL)
            ).astype(out_ref.dtype)
            continue
        ys = []
        for j in range(je):
            y = jax.lax.dot_general(
                w, a3[j], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (400, 841) f32, rows ordered (r,s,o)
            ys.append(y.astype(YDT))
        ybuf = jnp.stack(ys, axis=0)  # (je, 400, 841)
        if MODE == "noepi":
            out_ref[0, 0, j0:j0 + je] = jnp.broadcast_to(
                jnp.sum(ybuf.astype(jnp.float32)) * 1e-9, (je, C, KL)
            ).astype(out_ref.dtype)
            continue
        acc = jnp.zeros((je, C, 721), jnp.float32)
        for r in range(K):
            for s in range(K):
                blk = (r * K + s) * C
                off = r * SP + s
                acc = acc + ybuf[:, blk:blk + C, off:off + 721].astype(
                    jnp.float32)
        acc = jnp.maximum(acc + b_ref[:].astype(jnp.float32), 0.0)
        full = jnp.pad(acc, ((0, 0), (0, 0), (60, 60)))
        out_ref[0, 0, j0:j0 + je] = (
            full * m_ref[:].astype(jnp.float32)).astype(out_ref.dtype)


def conv_l2_pallas(xp, w2, bias, mask):
    """xp: (B, 29, 29, 16, 841) padded fused-lane volume (bf16).
    w2: (400, 400) = w[(p,q,c), (r,s,o)].  Returns (B, 25, 25, 16, 841)
    relu(conv+bias) rows in the same padded-lane frame (halos zeroed)."""
    b = xp.shape[0]
    je_list = tuple(
        (j0, min(JCH, S - j0)) for j0 in range(0, S, JCH)
    )
    kern = functools.partial(_kernel, je_list=je_list)
    row_spec = lambda p: pl.BlockSpec(  # noqa: E731
        (1, 1, SP, C, KL), lambda bi, ii, p=p: (bi, ii + p, 0, 0, 0),
        memory_space=pltpu.VMEM,
    )
    return pl.pallas_call(
        kern,
        grid=(b, S),
        in_specs=[row_spec(p) for p in range(K)] + [
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, S, C, KL), lambda bi, ii: (bi, ii, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((b, S, S, C, KL), xp.dtype),
        scratch_shapes=(
            [pltpu.VMEM((JCH, K * K * C, KL), xp.dtype)]
            if BUILD == "scratch" else []
        ),
    )(*([xp] * K), w2, bias, mask)


def to_fused_layout(x):
    """(B, 25, 25, 25, 25, 16) -> (B, 29, 29, 16, 841) padded bf16."""
    b = x.shape[0]
    xp = jnp.pad(x, ((0, 0), (2, 2), (2, 2), (2, 2), (2, 2), (0, 0)))
    xp = jnp.transpose(xp, (0, 1, 2, 5, 3, 4)).reshape(b, SP, SP, C, KL)
    return xp


def from_fused_layout(y):
    """(B, 25, 25, 16, 841) -> (B, 25, 25, 25, 25, 16)."""
    b = y.shape[0]
    y = y.reshape(b, S, S, C, SP, SP)[:, :, :, :, 2:2 + S, 2:2 + S]
    return jnp.transpose(y, (0, 1, 2, 4, 5, 3))


def pack_weight(w):
    """(5,5,5,5,16,16) -> (400, 400) [(p,q,c),(r,s,o)]."""
    return jnp.transpose(w, (0, 1, 4, 2, 3, 5)).reshape(K * K * C, K * K * C)


def make_mask():
    m = np.zeros((SP, SP), np.float32)
    m[2:2 + S, 2:2 + S] = 1.0
    return jnp.asarray(m.reshape(1, 1, KL), jnp.bfloat16)


def check():
    from ncnet_tpu.ops.conv4d import conv4d

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, S, S, S, S, C)) * 0.1, jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(K, K, K, K, C, C)) * 0.05, jnp.bfloat16)
    bias = jnp.asarray(rng.normal(size=(C,)) * 0.1, jnp.bfloat16)

    ref = jax.nn.relu(conv4d(x, w, bias, variant="unroll"))
    got = from_fused_layout(
        conv_l2_pallas(
            to_fused_layout(x), pack_weight(w),
            bias.reshape(1, C, 1), make_mask(),
        )
    )
    err = np.max(np.abs(np.asarray(got, np.float32) -
                        np.asarray(ref, np.float32)))
    rel = err / max(1e-6, float(np.max(np.abs(np.asarray(ref, np.float32)))))
    print(f"parity: max abs err {err:.4g} (rel {rel:.3%})")
    assert rel < 0.05, "numerics mismatch"


def main():
    print(f"device={jax.devices()[0].device_kind} n_volumes={B} "
          f"(bench shape: bs4 pairs = 8 batch-folded volumes) mode={MODE}")
    if MODE == "full":
        check()

    def make_input(key):
        k1, k2, k3 = jax.random.split(key, 3)
        return (
            jax.random.normal(k1, (B, S, S, S, S, C), jnp.bfloat16) * 0.1,
            jax.random.normal(k2, (K,) * 4 + (C, C), jnp.bfloat16) * 0.05,
            jax.random.normal(k3, (C,), jnp.bfloat16) * 0.1,
        )

    def step_layout_only(carry):
        x, w, bias = carry
        xp = to_fused_layout(x)
        eps = (jnp.sum(xp.astype(jnp.float32)) * 1e-12).astype(x.dtype)
        return x + eps, w, bias

    def step_kernel(carry):
        x, w, bias = carry
        out = conv_l2_pallas(to_fused_layout(x), pack_weight(w),
                             bias.reshape(1, C, 1), make_mask())
        eps = (jnp.sum(out.astype(jnp.float32)) * 1e-12).astype(x.dtype)
        return x + eps, w, bias

    def step_xla(carry):
        from ncnet_tpu.ops.conv4d import conv4d

        x, w, bias = carry
        out = jax.nn.relu(conv4d(x, w, bias, variant="coutfold"))
        eps = (jnp.sum(out.astype(jnp.float32)) * 1e-12).astype(x.dtype)
        return x + eps, w, bias

    ms_layout = timeit(step_layout_only, make_input, per=B, n_long=8)
    ms_kernel = timeit(step_kernel, make_input, per=B, n_long=8)
    ms_xla = timeit(step_xla, make_input, per=B, n_long=8)
    print(f"layout conversion only : {ms_layout:7.3f} ms/volume")
    print(f"pallas kernel (+layout): {ms_kernel:7.3f} ms/volume")
    print(f"xla coutfold reference : {ms_xla:7.3f} ms/volume")


if __name__ == "__main__":
    main()
