#!/usr/bin/env python
"""Export ncnet_tpu event logs as Chrome trace-event JSON (Perfetto-viewable).

The span events (``ncnet_tpu/observability/tracing.py``) give the event log
hierarchical structure — ``span``/``ph="B"`` at entry, ``ph="E"`` with a
monotonic ``dur_s`` at exit, ``parent``/``tid`` stamped on the ``B``.  This
tool renders any such log (or several: resumed runs append to one file,
sharded runs write many) into the Chrome trace-event format that
https://ui.perfetto.dev and chrome://tracing load directly:

  * every CLOSED span becomes one complete ("X") slice, timed by the entry
    event's wall clock and the exit event's monotonic duration;
  * an UNCLOSED span (the process was SIGKILLed mid-span, or the sink died)
    is emitted as a bare "B" — Perfetto renders it as a slice that never
    ends, which is exactly the postmortem signal: *this* is what was in
    flight when the process died;
  * non-span events (step, checkpoint_commit, tier_selected, retry,
    quarantine, …) become instant ("i") markers on a dedicated track, so
    the trace shows the run's milestones against its time structure;
  * ``request_timeline`` events (the serving plane's per-request
    attribution, ncnet_tpu/serving/service.py) become Perfetto ASYNC
    nestable slices keyed by request id: one enclosing ``req <id>
    [<outcome>]`` slice spanning the request's end-to-end wall, with the
    queue/device/fetch segments nested inside it — laid out from the
    event's ``t0`` (wall-clock submission) plus the segment durations,
    which sum to ``total_ms`` by construction, so the slices tile the
    request exactly;
  * ``quality`` and ``metrics`` events become counter ("C") tracks —
    Perfetto renders them as stacked value-over-time plots, so a
    match-quality drift (observability/quality.py) is visible on the SAME
    timeline as the spans that caused it (a tier demotion's quality cost
    lines up under its ``tier_recovery`` span).  Per-pair signal lists
    collapse to their mean per event; metrics snapshots contribute their
    scalars (and timers their ``last_s``);
  * each run id in the lineage gets its own trace process, each recorded
    thread its own track, with "M" metadata records naming them.

Replay is torn-tail tolerant (``replay_events``): a log whose writer was
SIGKILLed mid-append still exports minus at most the torn trailing line.

Usage::

    python tools/trace_export.py <events.jsonl> [more.jsonl ...] [-o trace.json]

``-o -`` writes the trace JSON to stdout.  Default output:
``<first input>.trace.json``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ncnet_tpu.observability.events import replay_events  # noqa: E402

# span "B" bookkeeping fields that should not be duplicated into args
_B_META = ("t", "run", "seq", "event", "ph", "name", "span", "parent", "tid")
# instant-event fields that are envelope, not payload
_I_META = ("t", "run", "seq", "event")


def _us(t: float) -> float:
    return t * 1e6


def _finite_mean(vals) -> "float | None":
    xs = [float(v) for v in vals
          if isinstance(v, (int, float)) and not isinstance(v, bool)
          and float(v) == float(v)]
    return sum(xs) / len(xs) if xs else None


def timeline_events(e: dict, pid: int) -> List[Dict[str, Any]]:
    """Render one ``request_timeline`` event as Perfetto async nestable
    slices ("b"/"e" pairs sharing ``id`` = the request id): the enclosing
    request slice plus its queue/device/fetch segments in submission
    order.  Returns [] when the event carries no usable total."""
    total_ms = e.get("total_ms")
    t0 = e.get("t0")
    if not isinstance(total_ms, (int, float)) \
            or not isinstance(t0, (int, float)):
        return []
    rid = str(e.get("request", "?"))
    run = e.get("run", "?")
    # request ids restart per service process: scope the async id by run
    # so two lineages in one file cannot interleave their slices
    async_id = f"{run}/{rid}"
    cat = "serve_request"
    outcome = e.get("outcome", "?")
    args = {k: e[k] for k in
            ("request", "client", "bucket", "outcome", "replica", "where",
             "attempts", "queue_ms", "device_ms", "fetch_ms", "total_ms")
            if k in e}
    out: List[Dict[str, Any]] = []

    def slice_pair(name: str, start_s: float, dur_ms: float,
                   slice_args: Dict[str, Any]) -> None:
        out.append({"ph": "b", "cat": cat, "id": async_id, "name": name,
                    "pid": pid, "tid": 0, "ts": _us(start_s),
                    "args": slice_args})
        out.append({"ph": "e", "cat": cat, "id": async_id, "name": name,
                    "pid": pid, "tid": 0,
                    "ts": _us(start_s + dur_ms * 1e-3)})

    slice_pair(f"req {rid} [{outcome}]", t0, total_ms, args)
    cursor = float(t0)
    for seg in ("queue_ms", "device_ms", "fetch_ms"):
        dur = e.get(seg)
        if not isinstance(dur, (int, float)):
            continue
        slice_pair(seg[:-3], cursor, float(dur), {seg: dur})
        cursor += float(dur) * 1e-3
    return out


def counter_events(e: dict) -> List[Dict[str, Any]]:
    """Render one ``quality`` or ``metrics`` event as Chrome counter ("C")
    args — numbers only (a counter track cannot plot strings or NaN).
    Returns [] when nothing numeric survives."""
    args: Dict[str, float] = {}
    if e.get("event") == "quality":
        name = f"quality/{e.get('scope', '?')}/{e.get('tier') or '?'}"
        for sig, vals in (e.get("signals") or {}).items():
            m = _finite_mean(vals if isinstance(vals, list) else [vals])
            if m is not None:
                args[sig] = m
        pck = e.get("pck")
        if isinstance(pck, list):
            m = _finite_mean(pck)
            if m is not None:
                args["pck"] = m
    else:  # metrics
        name = f"metrics/{e.get('scope', '?')}"
        for k, v in (e.get("metrics") or {}).items():
            if isinstance(v, dict):
                # timer/histogram snapshot: the most recent wall (timers)
                # or the running mean (histograms) is the plottable scalar
                v = v.get("last_s", v.get("mean"))
            m = _finite_mean([v])
            if m is not None:
                args[k] = m
    if not args:
        return []
    return [{"name": name, "args": args}]


def build_trace(paths: List[str]) -> Dict[str, Any]:
    """One Chrome trace document over every given event log."""
    trace_events: List[Dict[str, Any]] = []
    headers: List[Dict[str, Any]] = []
    pid_of_run: Dict[str, int] = {}
    tid_of: Dict[Tuple[int, Any], int] = {}  # (pid, raw tid) -> track id

    def pid_for(run: Any, header: Dict[str, Any]) -> int:
        key = str(run)
        if key not in pid_of_run:
            pid_of_run[key] = len(pid_of_run) + 1
            trace_events.append({
                "ph": "M", "name": "process_name", "pid": pid_of_run[key],
                "tid": 0, "args": {"name": (
                    f"run {key} @ {header.get('host', '?')}"
                    f" [{header.get('device_kind') or 'no-device'}]")},
            })
            trace_events.append({
                "ph": "M", "name": "thread_name", "pid": pid_of_run[key],
                "tid": 0, "args": {"name": "events"},
            })
        return pid_of_run[key]

    def tid_for(pid: int, raw) -> int:
        key = (pid, raw)
        if key not in tid_of:
            # track 0 is the instant-marker track; spans start at 1
            tid_of[key] = 1 + sum(1 for k in tid_of if k[0] == pid)
            trace_events.append({
                "ph": "M", "name": "thread_name", "pid": pid,
                "tid": tid_of[key], "args": {"name": f"thread {raw}"},
            })
        return tid_of[key]

    for path in paths:
        header, events = replay_events(path)
        head = header.get("header", {})
        headers.append({"path": path, **head})
        # pair span B/E by (run, span id) — ids are process-unique ints, so
        # the run id disambiguates resume lineages appending to one file
        open_spans: Dict[Tuple[Any, Any], Dict[str, Any]] = {}
        for e in events:
            run = e.get("run", "?")
            pid = pid_for(run, head)
            if e.get("event") == "request_timeline":
                # the per-request attribution renders as async slices (no
                # instant marker — the slices ARE the event's display)
                trace_events.extend(timeline_events(e, pid))
                continue
            if e.get("event") == "quality" or \
                    isinstance(e.get("metrics"), dict):
                # value-over-time payloads render as counter tracks —
                # Perfetto plots them beside the spans, which is exactly
                # how a quality drift is seen against its cause.  Registry
                # flushes carry their snapshot under `metrics` whatever the
                # event name (fit flushes as `metrics`, the eval loops as
                # `eval_summary`): the snapshot becomes counter samples
                # either way, and an event that is MORE than a flush
                # (eval_summary's completed/quarantined fields) also keeps
                # its instant marker below, minus the plotted snapshot.
                for c in counter_events(e):
                    trace_events.append({
                        "ph": "C", "name": c["name"], "pid": pid, "tid": 0,
                        "ts": _us(float(e.get("t", 0.0))),
                        "cat": "counter", "args": c["args"],
                    })
                if e.get("event") in ("quality", "metrics"):
                    continue
                e = {k: v for k, v in e.items() if k != "metrics"}
            if e.get("event") != "span":
                args = {k: v for k, v in e.items() if k not in _I_META}
                trace_events.append({
                    "ph": "i", "name": str(e.get("event")), "pid": pid,
                    "tid": 0, "ts": _us(float(e.get("t", 0.0))), "s": "t",
                    "cat": "event", "args": args,
                })
                continue
            if e.get("ph") == "B":
                open_spans[(run, e.get("span"))] = e
                continue
            b = open_spans.pop((run, e.get("span")), None)
            if b is None:
                continue  # E without B (sink bound mid-span): undisplayable
            args = {k: v for k, v in b.items() if k not in _B_META}
            if e.get("error"):
                args["error"] = e["error"]
            trace_events.append({
                "ph": "X", "name": str(b.get("name")), "pid": pid,
                "tid": tid_for(pid, b.get("tid")),
                "ts": _us(float(b.get("t", 0.0))),
                "dur": _us(float(e.get("dur_s") or 0.0)),
                "cat": "span", "args": args,
            })
        # unclosed spans: what was in flight at SIGKILL.  A bare "B" is
        # valid trace JSON; Perfetto draws it as a never-ending slice.
        for (run, _), b in sorted(open_spans.items(),
                                  key=lambda kv: kv[1].get("t", 0.0)):
            pid = pid_of_run[str(run)]
            args = {k: v for k, v in b.items() if k not in _B_META}
            args["unclosed"] = True
            trace_events.append({
                "ph": "B", "name": str(b.get("name")), "pid": pid,
                "tid": tid_for(pid, b.get("tid")),
                "ts": _us(float(b.get("t", 0.0))),
                "cat": "span", "args": args,
            })

    return {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {"logs": headers, "exporter": "ncnet_tpu trace_export"},
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Export ncnet_tpu event logs as Chrome trace JSON")
    ap.add_argument("logs", nargs="+", help="events.jsonl file(s)")
    ap.add_argument("-o", "--output", default=None,
                    help="output path ('-' for stdout; default: "
                         "<first input>.trace.json)")
    args = ap.parse_args(argv)
    trace = build_trace(args.logs)
    out = args.output or (args.logs[0] + ".trace.json")
    text = json.dumps(trace)
    if out == "-":
        sys.stdout.write(text + "\n")
    else:
        with open(out, "w") as f:
            f.write(text)
        n_spans = sum(1 for e in trace["traceEvents"] if e["ph"] in "XB")
        sys.stderr.write(
            f"wrote {out}: {n_spans} spans, "
            f"{len(trace['traceEvents'])} trace events — open in "
            "https://ui.perfetto.dev\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
