#!/usr/bin/env python
"""Export ncnet_tpu event logs as Chrome trace-event JSON (Perfetto-viewable).

The span events (``ncnet_tpu/observability/tracing.py``) give the event log
hierarchical structure — ``span``/``ph="B"`` at entry, ``ph="E"`` with a
monotonic ``dur_s`` at exit, ``parent``/``tid`` stamped on the ``B``.  This
tool renders any such log (or several: resumed runs append to one file,
sharded runs write many) into the Chrome trace-event format that
https://ui.perfetto.dev and chrome://tracing load directly:

  * every CLOSED span becomes one complete ("X") slice, timed by the entry
    event's wall clock and the exit event's monotonic duration;
  * an UNCLOSED span (the process was SIGKILLed mid-span, or the sink died)
    is emitted as a bare "B" — Perfetto renders it as a slice that never
    ends, which is exactly the postmortem signal: *this* is what was in
    flight when the process died;
  * non-span events (step, checkpoint_commit, tier_selected, retry,
    quarantine, …) become instant ("i") markers on a dedicated track, so
    the trace shows the run's milestones against its time structure;
  * ``request_timeline`` events (the serving plane's per-request
    attribution, ncnet_tpu/serving/service.py) become Perfetto ASYNC
    nestable slices keyed by request id: one enclosing ``req <id>
    [<outcome>]`` slice spanning the request's end-to-end wall, with the
    queue/device/fetch segments nested inside it — laid out from the
    event's ``t0`` (wall-clock submission) plus the segment durations,
    which sum to ``total_ms`` by construction, so the slices tile the
    request exactly;
  * ``quality`` and ``metrics`` events become counter ("C") tracks —
    Perfetto renders them as stacked value-over-time plots, so a
    match-quality drift (observability/quality.py) is visible on the SAME
    timeline as the spans that caused it (a tier demotion's quality cost
    lines up under its ``tier_recovery`` span).  Per-pair signal lists
    collapse to their mean per event; metrics snapshots contribute their
    scalars (and timers their ``last_s``);
  * each run id in the lineage gets its own trace process, each recorded
    thread its own track, with "M" metadata records naming them.

Replay is torn-tail tolerant (``replay_events``): a log whose writer was
SIGKILLed mid-append still exports minus at most the torn trailing line.

**Pod federation** (``--federate``): N event logs from DIFFERENT hosts
merge into ONE trace.  Three things plain multi-file export cannot do:

  * **clock alignment** — every wire round trip leaves ``clock_sync``
    events (``serving/wire.py``: NTP-style half-RTT offset samples,
    ``offset_s`` = peer wall − local wall).  The exporter builds the
    sync graph over RUN IDS (hostnames collide in test pods; run ids
    never do), takes the minimum-RTT sample per edge, and BFS-propagates
    corrections from the first log's run so every process's timestamps
    land on one pod clock.  A run unreachable in the graph keeps its raw
    clock, is listed under ``otherData.federation.unaligned``, and gets
    NO flow arrows — an unaligned arrow would be a wrong arrow;
  * **router request slices** — each ``route_admit`` paired with its
    terminal ``route_*`` event becomes an "X" slice on the router run's
    ``requests`` track, so the pod view shows the edge-observed request
    wall above the backend's queue/device/fetch attribution;
  * **cross-host flow arrows** — requests stamped with a pod trace id
    (``observability/tracing.py``) link router slice → backend request
    slice(s) with Chrome flow events ("s"/"t"/"f" sharing the trace id),
    so one click in Perfetto follows a request across processes —
    including a failover's second backend.

Usage::

    python tools/trace_export.py <events.jsonl> [more.jsonl ...] [-o trace.json]
    python tools/trace_export.py --federate router.jsonl b0.jsonl b1.jsonl \
        [-o pod.trace.json]

``-o -`` writes the trace JSON to stdout.  Default output:
``<first input>.trace.json``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ncnet_tpu.observability.events import replay_events  # noqa: E402

# span "B" bookkeeping fields that should not be duplicated into args
_B_META = ("t", "run", "seq", "event", "ph", "name", "span", "parent", "tid")
# instant-event fields that are envelope, not payload
_I_META = ("t", "run", "seq", "event")


def _us(t: float) -> float:
    return t * 1e6


def _finite_mean(vals) -> "float | None":
    xs = [float(v) for v in vals
          if isinstance(v, (int, float)) and not isinstance(v, bool)
          and float(v) == float(v)]
    return sum(xs) / len(xs) if xs else None


def timeline_events(e: dict, pid: int) -> List[Dict[str, Any]]:
    """Render one ``request_timeline`` event as Perfetto async nestable
    slices ("b"/"e" pairs sharing ``id`` = the request id): the enclosing
    request slice plus its queue/device/fetch segments in submission
    order.  Returns [] when the event carries no usable total."""
    total_ms = e.get("total_ms")
    t0 = e.get("t0")
    if not isinstance(total_ms, (int, float)) \
            or not isinstance(t0, (int, float)):
        return []
    rid = str(e.get("request", "?"))
    run = e.get("run", "?")
    # request ids restart per service process: scope the async id by run
    # so two lineages in one file cannot interleave their slices
    async_id = f"{run}/{rid}"
    cat = "serve_request"
    outcome = e.get("outcome", "?")
    args = {k: e[k] for k in
            ("request", "client", "bucket", "outcome", "replica", "where",
             "attempts", "queue_ms", "device_ms", "fetch_ms", "total_ms")
            if k in e}
    out: List[Dict[str, Any]] = []

    def slice_pair(name: str, start_s: float, dur_ms: float,
                   slice_args: Dict[str, Any]) -> None:
        out.append({"ph": "b", "cat": cat, "id": async_id, "name": name,
                    "pid": pid, "tid": 0, "ts": _us(start_s),
                    "args": slice_args})
        out.append({"ph": "e", "cat": cat, "id": async_id, "name": name,
                    "pid": pid, "tid": 0,
                    "ts": _us(start_s + dur_ms * 1e-3)})

    slice_pair(f"req {rid} [{outcome}]", t0, total_ms, args)
    cursor = float(t0)
    for seg in ("queue_ms", "device_ms", "fetch_ms"):
        dur = e.get(seg)
        if not isinstance(dur, (int, float)):
            continue
        slice_pair(seg[:-3], cursor, float(dur), {seg: dur})
        cursor += float(dur) * 1e-3
    return out


def counter_events(e: dict) -> List[Dict[str, Any]]:
    """Render one ``quality`` or ``metrics`` event as Chrome counter ("C")
    args — numbers only (a counter track cannot plot strings or NaN).
    Returns [] when nothing numeric survives."""
    args: Dict[str, float] = {}
    if e.get("event") == "quality":
        name = f"quality/{e.get('scope', '?')}/{e.get('tier') or '?'}"
        for sig, vals in (e.get("signals") or {}).items():
            m = _finite_mean(vals if isinstance(vals, list) else [vals])
            if m is not None:
                args[sig] = m
        pck = e.get("pck")
        if isinstance(pck, list):
            m = _finite_mean(pck)
            if m is not None:
                args["pck"] = m
    else:  # metrics
        name = f"metrics/{e.get('scope', '?')}"
        for k, v in (e.get("metrics") or {}).items():
            if isinstance(v, dict):
                # timer/histogram snapshot: the most recent wall (timers)
                # or the running mean (histograms) is the plottable scalar
                v = v.get("last_s", v.get("mean"))
            m = _finite_mean([v])
            if m is not None:
                args[k] = m
    if not args:
        return []
    return [{"name": name, "args": args}]


class _TraceBuilder:
    """Incremental Chrome-trace assembly shared by the single-log and the
    federated exports: process/track allocation plus the per-log event
    rendering loop."""

    def __init__(self) -> None:
        self.trace_events: List[Dict[str, Any]] = []
        self.headers: List[Dict[str, Any]] = []
        self.pid_of_run: Dict[str, int] = {}
        # (pid, raw tid) -> track id
        self.tid_of: Dict[Tuple[int, Any], int] = {}

    def pid_for(self, run: Any, header: Dict[str, Any]) -> int:
        key = str(run)
        if key not in self.pid_of_run:
            self.pid_of_run[key] = len(self.pid_of_run) + 1
            self.trace_events.append({
                "ph": "M", "name": "process_name",
                "pid": self.pid_of_run[key],
                "tid": 0, "args": {"name": (
                    f"run {key} @ {header.get('host', '?')}"
                    f" [{header.get('device_kind') or 'no-device'}]")},
            })
            self.trace_events.append({
                "ph": "M", "name": "thread_name",
                "pid": self.pid_of_run[key],
                "tid": 0, "args": {"name": "events"},
            })
        return self.pid_of_run[key]

    def tid_for(self, pid: int, raw) -> int:
        key = (pid, raw)
        if key not in self.tid_of:
            # track 0 is the instant-marker track; spans start at 1
            self.tid_of[key] = 1 + sum(1 for k in self.tid_of
                                       if k[0] == pid)
            self.trace_events.append({
                "ph": "M", "name": "thread_name", "pid": pid,
                "tid": self.tid_of[key],
                "args": {"name": (raw if isinstance(raw, str)
                                  else f"thread {raw}")},
            })
        return self.tid_of[key]

    def add_log(self, path: str, head: Dict[str, Any],
                events: List[Dict[str, Any]]) -> None:
        trace_events = self.trace_events
        pid_for, tid_for = self.pid_for, self.tid_for
        self.headers.append({"path": path, **head})
        pid_of_run = self.pid_of_run
        # pair span B/E by (run, span id) — ids are process-unique ints, so
        # the run id disambiguates resume lineages appending to one file
        open_spans: Dict[Tuple[Any, Any], Dict[str, Any]] = {}
        for e in events:
            run = e.get("run", "?")
            pid = pid_for(run, head)
            if e.get("event") == "request_timeline":
                # the per-request attribution renders as async slices (no
                # instant marker — the slices ARE the event's display)
                trace_events.extend(timeline_events(e, pid))
                continue
            if e.get("event") == "quality" or \
                    isinstance(e.get("metrics"), dict):
                # value-over-time payloads render as counter tracks —
                # Perfetto plots them beside the spans, which is exactly
                # how a quality drift is seen against its cause.  Registry
                # flushes carry their snapshot under `metrics` whatever the
                # event name (fit flushes as `metrics`, the eval loops as
                # `eval_summary`): the snapshot becomes counter samples
                # either way, and an event that is MORE than a flush
                # (eval_summary's completed/quarantined fields) also keeps
                # its instant marker below, minus the plotted snapshot.
                for c in counter_events(e):
                    trace_events.append({
                        "ph": "C", "name": c["name"], "pid": pid, "tid": 0,
                        "ts": _us(float(e.get("t", 0.0))),
                        "cat": "counter", "args": c["args"],
                    })
                if e.get("event") in ("quality", "metrics"):
                    continue
                e = {k: v for k, v in e.items() if k != "metrics"}
            if e.get("event") != "span":
                args = {k: v for k, v in e.items() if k not in _I_META}
                trace_events.append({
                    "ph": "i", "name": str(e.get("event")), "pid": pid,
                    "tid": 0, "ts": _us(float(e.get("t", 0.0))), "s": "t",
                    "cat": "event", "args": args,
                })
                continue
            if e.get("ph") == "B":
                open_spans[(run, e.get("span"))] = e
                continue
            b = open_spans.pop((run, e.get("span")), None)
            if b is None:
                continue  # E without B (sink bound mid-span): undisplayable
            args = {k: v for k, v in b.items() if k not in _B_META}
            if e.get("error"):
                args["error"] = e["error"]
            trace_events.append({
                "ph": "X", "name": str(b.get("name")), "pid": pid,
                "tid": tid_for(pid, b.get("tid")),
                "ts": _us(float(b.get("t", 0.0))),
                "dur": _us(float(e.get("dur_s") or 0.0)),
                "cat": "span", "args": args,
            })
        # unclosed spans: what was in flight at SIGKILL.  A bare "B" is
        # valid trace JSON; Perfetto draws it as a never-ending slice.
        for (run, _), b in sorted(open_spans.items(),
                                  key=lambda kv: kv[1].get("t", 0.0)):
            pid = pid_of_run[str(run)]
            args = {k: v for k, v in b.items() if k not in _B_META}
            args["unclosed"] = True
            trace_events.append({
                "ph": "B", "name": str(b.get("name")), "pid": pid,
                "tid": tid_for(pid, b.get("tid")),
                "ts": _us(float(b.get("t", 0.0))),
                "cat": "span", "args": args,
            })

    def doc(self, federation: "Dict[str, Any] | None" = None
            ) -> Dict[str, Any]:
        other: Dict[str, Any] = {"logs": self.headers,
                                 "exporter": "ncnet_tpu trace_export"}
        if federation is not None:
            other["federation"] = federation
        return {
            "traceEvents": self.trace_events,
            "displayTimeUnit": "ms",
            "otherData": other,
        }


def _load_logs(paths: List[str]
               ) -> List[Tuple[str, Dict[str, Any], List[Dict[str, Any]]]]:
    out = []
    for path in paths:
        header, events = replay_events(path)
        out.append((path, header.get("header", {}), events))
    return out


def build_trace(paths: List[str]) -> Dict[str, Any]:
    """One Chrome trace document over every given event log."""
    b = _TraceBuilder()
    for path, head, events in _load_logs(paths):
        b.add_log(path, head, events)
    return b.doc()


# terminal route_* events that close a router request slice (mirrors the
# router's outcome-total contract; `route_admit` opens the slice)
_ROUTE_TERMINALS = ("route_result", "route_deadline", "route_shed",
                    "route_quarantine")


def _clock_corrections(
    logs, warn,
) -> Tuple[Dict[str, float], List[str], List[str]]:
    """Per-run additive clock corrections from the ``clock_sync`` graph.

    Nodes are run ids; an edge is the MINIMUM-RTT sample between a pair
    (lowest-RTT exchange = tightest offset bound, the classic NTP filter).
    The first log's first run is the reference (correction 0); BFS
    propagates ``corrected = t + c[run]`` both ways across each edge.
    Returns ``(corrections, aligned, unaligned)``; unaligned runs keep
    correction 0 and the caller must not draw cross-host arrows to them.
    """
    runs: List[str] = []
    for _, head, events in logs:
        for e in events:
            r = str(e.get("run", "?"))
            if r not in runs:
                runs.append(r)
    # min-RTT sample per undirected pair, kept directed as measured
    best: Dict[Tuple[str, str], Tuple[float, float]] = {}
    for _, _, events in logs:
        for e in events:
            if e.get("event") != "clock_sync":
                continue
            a, b = str(e.get("run", "?")), e.get("peer_run")
            off, rtt = e.get("offset_s"), e.get("rtt_s")
            if not b or not isinstance(off, (int, float)) \
                    or not isinstance(rtt, (int, float)) or rtt < 0:
                continue
            key = tuple(sorted((a, str(b))))
            if key not in best or rtt < best[key][0]:
                # store as (rtt, offset a->b) normalized to key order
                o = float(off) if (a, str(b)) == key else -float(off)
                best[key] = (float(rtt), o)
    adj: Dict[str, List[Tuple[str, float]]] = {}
    for (a, b), (_, off) in best.items():
        # off = wall_b − wall_a at one instant ⇒ c[b] = c[a] − off
        adj.setdefault(a, []).append((b, -off))
        adj.setdefault(b, []).append((a, +off))
    corr: Dict[str, float] = {}
    if runs:
        ref = runs[0]
        corr[ref] = 0.0
        queue = [ref]
        while queue:
            u = queue.pop(0)
            for v, d in adj.get(u, []):
                if v not in corr:
                    corr[v] = corr[u] + d
                    queue.append(v)
    aligned = [r for r in runs if r in corr]
    unaligned = [r for r in runs if r not in corr]
    for r in unaligned:
        corr[r] = 0.0
    if unaligned:
        warn(f"no clock_sync path to run(s) {', '.join(unaligned)}: "
             "their timestamps stay UNALIGNED (raw local clock) and no "
             "cross-host flow arrows are drawn to them")
    return corr, aligned, unaligned


def build_federated_trace(paths: List[str],
                          warn=None) -> Dict[str, Any]:
    """N per-host event logs → ONE pod trace: clock-skew-corrected
    timestamps, per-run process tracks, router request slices, and
    trace-id flow arrows stitching each pod request across processes.
    ``warn`` (a callable, default stderr) receives human-readable
    degradation notes (unaligned runs)."""
    if warn is None:
        def warn(msg: str) -> None:
            sys.stderr.write(f"federate: WARNING: {msg}\n")
    logs = _load_logs(paths)
    corr, aligned, unaligned = _clock_corrections(logs, warn)
    aligned_set = set(aligned)
    # shift every wall stamp onto the pod clock BEFORE rendering, so the
    # ordinary renderer needs no knowledge of federation
    for _, _, events in logs:
        for e in events:
            c = corr.get(str(e.get("run", "?")), 0.0)
            if isinstance(e.get("t"), (int, float)):
                e["t"] = float(e["t"]) + c
            if e.get("event") == "request_timeline" \
                    and isinstance(e.get("t0"), (int, float)):
                e["t0"] = float(e["t0"]) + c
    b = _TraceBuilder()
    for path, head, events in logs:
        b.add_log(path, head, events)
    # --- router request slices: route_admit paired with its terminal ----
    # (run, request) -> admit event / terminal event
    admits: Dict[Tuple[str, str], Dict[str, Any]] = {}
    terminals: Dict[Tuple[str, str], Dict[str, Any]] = {}
    # trace id -> router slice / backend timeline slices, for the flows
    router_of: Dict[str, List[Tuple[str, float, float]]] = {}
    backend_of: Dict[str, List[Tuple[str, float, float]]] = {}
    head_of_run: Dict[str, Dict[str, Any]] = {}
    for _, head, events in logs:
        for e in events:
            run = str(e.get("run", "?"))
            head_of_run.setdefault(run, head)
            name = e.get("event")
            rid = e.get("request")
            if name == "route_admit" and rid is not None:
                admits[(run, str(rid))] = e
            elif name in _ROUTE_TERMINALS and rid is not None:
                terminals.setdefault((run, str(rid)), e)
            elif name == "request_timeline" and e.get("trace") \
                    and isinstance(e.get("t0"), (int, float)) \
                    and isinstance(e.get("total_ms"), (int, float)):
                backend_of.setdefault(str(e["trace"]), []).append(
                    (run, float(e["t0"]),
                     float(e["t0"]) + float(e["total_ms"]) * 1e-3))
    n_router_slices = 0
    for (run, rid), adm in sorted(
            admits.items(), key=lambda kv: kv[1].get("t", 0.0)):
        term = terminals.get((run, rid))
        if term is None:
            continue  # request still in flight when the log was cut
        t0 = float(adm.get("t", 0.0))
        t1 = max(t0, float(term.get("t", t0)))
        pid = b.pid_for(run, head_of_run.get(run, {}))
        tid = b.tid_for(pid, "requests")
        outcome = str(term.get("event", "?"))[len("route_"):]
        args = {k: adm[k] for k in ("request", "client", "trace")
                if k in adm}
        args["outcome"] = outcome
        b.trace_events.append({
            "ph": "X", "name": f"req {rid} [{outcome}]", "pid": pid,
            "tid": tid, "ts": _us(t0), "dur": _us(t1 - t0),
            "cat": "route_request", "args": args,
        })
        n_router_slices += 1
        tr = adm.get("trace") or term.get("trace")
        if tr:
            router_of.setdefault(str(tr), []).append((run, t0, t1))
    # --- cross-host flow arrows, keyed by trace id ----------------------
    # drawn ONLY between runs the sync graph aligned: a flow between
    # uncorrected clocks would render a confidently WRONG arrow
    n_flows = 0
    for tr, routers in sorted(router_of.items()):
        backends = sorted(backend_of.get(tr, []), key=lambda s: s[1])
        if not backends:
            continue
        involved = {r for r, _, _ in routers} | {r for r, _, _ in backends}
        if not involved <= aligned_set:
            continue
        run, t0, _ = routers[0]
        pid = b.pid_for(run, head_of_run.get(run, {}))
        b.trace_events.append({
            "ph": "s", "id": tr, "name": "pod_request",
            "cat": "pod_request", "pid": pid,
            "tid": b.tid_for(pid, "requests"), "ts": _us(t0),
        })
        for i, (brun, bt0, _bt1) in enumerate(backends):
            bpid = b.pid_for(brun, head_of_run.get(brun, {}))
            b.trace_events.append({
                # "t" = intermediate step (a failover's first backend),
                # "f" with bp=e binds the arrowhead to the LAST slice
                "ph": ("f" if i == len(backends) - 1 else "t"),
                "id": tr, "name": "pod_request", "cat": "pod_request",
                "pid": bpid, "tid": b.tid_for(bpid, "requests"),
                "ts": _us(bt0),
                **({"bp": "e"} if i == len(backends) - 1 else {}),
            })
            # flow endpoints must land INSIDE a slice on their track:
            # mirror the backend's request wall as an X slice there
            b.trace_events.append({
                "ph": "X", "name": f"req[{tr[:8]}]", "pid": bpid,
                "tid": b.tid_for(bpid, "requests"), "ts": _us(bt0),
                "dur": _us(max(0.0, _bt1 - bt0)),
                "cat": "serve_request", "args": {"trace": tr},
            })
            n_flows += 1
    federation = {
        "runs": {r: {"correction_s": round(corr.get(r, 0.0), 6),
                     "aligned": r in aligned_set}
                 for r in sorted(set(corr))},
        "unaligned": sorted(unaligned),
        "router_slices": n_router_slices,
        "flows": n_flows,
    }
    return b.doc(federation=federation)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Export ncnet_tpu event logs as Chrome trace JSON")
    ap.add_argument("logs", nargs="+", help="events.jsonl file(s)")
    ap.add_argument("--federate", action="store_true",
                    help="merge the logs as one POD: clock-skew-corrected "
                         "timestamps from the clock_sync graph, router "
                         "request slices, and cross-host flow arrows "
                         "keyed by pod trace id")
    ap.add_argument("-o", "--output", default=None,
                    help="output path ('-' for stdout; default: "
                         "<first input>.trace.json)")
    args = ap.parse_args(argv)
    trace = (build_federated_trace(args.logs) if args.federate
             else build_trace(args.logs))
    out = args.output or (args.logs[0] + ".trace.json")
    text = json.dumps(trace)
    if out == "-":
        sys.stdout.write(text + "\n")
    else:
        with open(out, "w") as f:
            f.write(text)
        n_spans = sum(1 for e in trace["traceEvents"] if e["ph"] in "XB")
        fed = trace["otherData"].get("federation")
        extra = (f", {fed['router_slices']} router slices, "
                 f"{fed['flows']} flow steps" if fed else "")
        sys.stderr.write(
            f"wrote {out}: {n_spans} spans, "
            f"{len(trace['traceEvents'])} trace events{extra} — open in "
            "https://ui.perfetto.dev\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
