#!/usr/bin/env python
"""Resumable sharded builder for the retrieval tier's coarse-volume index.

The scatter-gather retrieval tier (``ncnet_tpu/retrieval/``) serves cached
COARSE volumes — per-pano pooled descriptive grids 1/factor^4 the size of
dense features — out of the PR 14 feature store.  This tool walks a
shortlist's unique pano set, computes each pano's coarse volume, commits
it under the coarse generation (``coarse_fingerprint``), and writes the
durable index manifest (``coarse_index.shard<i>_of_<n>.json``) mapping
pano names to content digests that shard hosts and the coordinator load.

Same robustness contract as ``build_feature_store.py``:

  * each pano builds under ``run_isolated`` — bounded retry + backoff,
    quarantine into the per-shard run manifest instead of aborting;
    exit 2 while quarantined panos remain;
  * resumable two ways: a pano already in this stripe's index manifest is
    skipped without decoding, and a recomputed pano whose entry already
    sits in the store is a verified HIT (two-phase commits mean a
    SIGKILLed rerun can never be fooled by a torn entry);
  * striping: ``--shard_index/--shard_count`` split the pano set across
    builder hosts; shard hosts later merge the per-stripe manifests
    (``load_index_manifests`` refuses mixed generations).

Extractors: ``--raw`` builds model-free color/gradient-statistics volumes
(numpy only, no jax import — the CPU chaos path); the default pools real
backbone features by ``--factor`` (pays compiles, matches serving).

Usage::

    python tools/build_coarse_index.py --store_dir /data/cstore \
        --inloc_shortlist .../densePE_top100_shortlist_cvpr18.mat \
        --pano_path datasets/inloc/pano/ --factor 4 --raw \
        [--checkpoint <ckpt> | --backbone tiny] [--n_panos 10] \
        [--shard_index 0 --shard_count 4] [--telemetry_dir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="Build the coarse-volume retrieval index from an InLoc "
                    "shortlist (resumable, per-shard manifests)")
    p.add_argument("--store_dir", required=True,
                   help="feature store root for the coarse generation "
                        "(shared across builder shards)")
    p.add_argument("--inloc_shortlist", type=str,
                   default="datasets/inloc/densePE_top100_shortlist_cvpr18"
                           ".mat")
    p.add_argument("--pano_path", type=str, default="datasets/inloc/pano/")
    p.add_argument("--factor", type=int, default=4,
                   help="coarse pooling factor (rides the fingerprint and "
                        "the index manifest)")
    p.add_argument("--raw", action="store_true",
                   help="model-free extractor (numpy only, no compiles) — "
                        "the CPU chaos-suite path")
    p.add_argument("--raw_grid", type=int, default=16,
                   help="raw extractor's fine grid the factor pools from")
    p.add_argument("--checkpoint", type=str, default="")
    p.add_argument("--backbone", type=str, default="",
                   help="trunk override when building without a checkpoint "
                        "(e.g. 'tiny' for the CPU smoke test)")
    p.add_argument("--image_size", type=int, default=3200)
    p.add_argument("--k_size", type=int, default=2)
    p.add_argument("--n_panos", type=int, default=10)
    p.add_argument("--shard_index", type=int, default=0)
    p.add_argument("--shard_count", type=int, default=1)
    p.add_argument("--retries", type=int, default=2)
    p.add_argument("--retry_backoff_s", type=float, default=0.5)
    p.add_argument("--telemetry_dir", type=str, default="",
                   help="open a structured event log here (replay with "
                        "run_report --store)")
    return p


def raw_base_fingerprint(grid: int) -> str:
    """The model-free extractor's synthetic base fingerprint — same
    ``<weights>-s<size>-k<k>-<dtype>`` shape as a backbone fingerprint so
    the store's weights-segment GC semantics apply unchanged."""
    return f"raw-s{int(grid)}-k0-f32"


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    out = sys.stdout.write

    if not 0 <= args.shard_index < max(1, args.shard_count):
        raise SystemExit(f"shard_index {args.shard_index} out of range for "
                         f"shard_count {args.shard_count}")

    from ncnet_tpu.data.datasets import load_image
    from ncnet_tpu.evaluation.resilience import (
        FaultPolicy,
        RunManifest,
        run_isolated,
    )
    from ncnet_tpu.observability import events as obs_events
    from ncnet_tpu.retrieval.index import (
        load_index_manifests,
        write_index_manifest,
    )
    from ncnet_tpu.retrieval.scoring import (
        coarse_volume_from_features,
        raw_coarse_volume,
    )
    from ncnet_tpu.store import (
        FeatureStore,
        coarse_fingerprint,
        content_digest,
    )
    _TOOLS = os.path.dirname(os.path.abspath(__file__))
    if _TOOLS not in sys.path:
        sys.path.insert(0, _TOOLS)
    from build_feature_store import unique_panos

    own_sink = None
    if args.telemetry_dir:
        from ncnet_tpu.observability.events import EventLog

        log_name = ("events.jsonl" if args.shard_count <= 1 else
                    f"events.shard{args.shard_index}.jsonl")
        own_sink = EventLog(
            os.path.join(args.telemetry_dir, log_name),
            run_meta={"tool": "build_coarse_index",
                      "shard_index": args.shard_index,
                      "shard_count": args.shard_count})
        obs_events.set_global_sink(own_sink)

    if args.raw:
        extractor = "raw"
        base_fp = raw_base_fingerprint(args.raw_grid)

        def volume_of(raw):
            return raw_coarse_volume(raw, args.factor, grid=args.raw_grid)
    else:
        # deferred so --raw (and --help) never pay jax startup
        import jax

        from ncnet_tpu.config import ModelConfig
        from ncnet_tpu.evaluation.inloc import make_pair_matcher
        from ncnet_tpu.store import backbone_fingerprint

        extractor = "backbone"
        base = ModelConfig(checkpoint=args.checkpoint, half_precision=True,
                           relocalization_k_size=args.k_size,
                           **({"backbone": args.backbone} if args.backbone
                              else {}))
        if args.checkpoint:
            from ncnet_tpu.models.checkpoint import load_params

            model_config, params = load_params(args.checkpoint, base)
            model_config = model_config.replace(
                half_precision=True, relocalization_k_size=args.k_size)
        else:
            from ncnet_tpu.models.ncnet import init_ncnet

            model_config = base
            params = init_ncnet(model_config, jax.random.key(1))
        base_fp = backbone_fingerprint(
            params, image_size=args.image_size, k_size=args.k_size,
            dtype="bf16")
        matcher = make_pair_matcher(
            model_config, params, do_softmax=True, both_directions=True,
            flip_direction=False, preprocess_image_size=args.image_size)

        def volume_of(raw):
            import numpy as np

            prepared = matcher.preprocess(raw[None])
            return coarse_volume_from_features(
                np.asarray(prepared.features, dtype=np.float32),
                args.factor)

    fingerprint = coarse_fingerprint(base_fp, args.factor)
    store = FeatureStore(args.store_dir, fingerprint, scope="coarse_build")
    shard_tag = f"shard{args.shard_index}_of_{max(1, args.shard_count)}"
    index_path = os.path.join(args.store_dir,
                              f"coarse_index.{shard_tag}.json")
    # fast-forward: panos already in this stripe's index manifest carry
    # their digest and are skipped without even decoding
    index_panos = {}
    if os.path.exists(index_path):
        try:
            prior = load_index_manifests(index_path)
            if prior["fingerprint"] == fingerprint \
                    and prior["factor"] == args.factor \
                    and prior["extractor"] == extractor:
                index_panos = dict(prior["panos"])
        except (OSError, ValueError):
            pass  # a foreign/torn manifest restarts the stripe, not the run

    panos = unique_panos(args.inloc_shortlist, args.n_panos)
    stripe = panos[args.shard_index::max(1, args.shard_count)]
    manifest = RunManifest(
        os.path.join(args.store_dir, f"coarse_manifest.{shard_tag}.json"),
        meta={"tool": "build_coarse_index", "fingerprint": fingerprint,
              "factor": args.factor, "extractor": extractor,
              "shortlist": os.path.basename(args.inloc_shortlist),
              "n_panos": args.n_panos,
              "shard_index": args.shard_index,
              "shard_count": max(1, args.shard_count)})
    policy = FaultPolicy(retries=args.retries,
                         backoff_s=args.retry_backoff_s, quarantine=True)

    t0 = time.perf_counter()
    built = skipped = 0
    for name in stripe:
        if name in index_panos:
            skipped += 1
            if not manifest.is_completed(name):
                manifest.complete(name)
            continue

        def work(name=name):
            raw = load_image(os.path.join(args.pano_path, name))
            digest = content_digest(raw)
            store.resolve(digest, lambda raw=raw: volume_of(raw))
            return digest

        ok, digest = run_isolated(name, work, policy=policy,
                                  manifest=manifest,
                                  label=f"pano {name}")
        if ok:
            built += 1
            index_panos[name] = digest
            write_index_manifest(
                index_path, fingerprint=fingerprint, factor=args.factor,
                extractor=extractor, panos=index_panos,
                meta={"shard_index": args.shard_index,
                      "shard_count": max(1, args.shard_count)})

    doc = {
        "tool": "build_coarse_index",
        "fingerprint": fingerprint,
        "extractor": extractor,
        "factor": args.factor,
        "shard": f"{args.shard_index}/{max(1, args.shard_count)}",
        "index": index_path,
        "stripe_panos": len(stripe),
        "built": built,
        "skipped_indexed": skipped,
        "quarantined": list(manifest.quarantined_ids),
        "store": store.flush_stats(tool="build_coarse_index"),
        "wall_s": round(time.perf_counter() - t0, 3),
    }
    out(json.dumps(doc, sort_keys=True) + "\n")
    store.close()
    if own_sink is not None:
        obs_events.set_global_sink(None)
        own_sink.close()
    return 2 if manifest.quarantined_ids else 0


if __name__ == "__main__":
    raise SystemExit(main())
