#!/usr/bin/env python
"""``top`` for the resident match service: a live console over the
introspection plane.

Polls a running service's ``/metrics`` (Prometheus text,
``ncnet_tpu/observability/export.py``) and ``/healthz`` (the unified
schema-versioned health document) and renders the operator view: service
state, queue depth against the elastic bound, the replica table (state,
routing score, EWMA wall, load, failures), per-bucket latency p50/p95/p99
derived from the cumulative ``_bucket`` series, and the SLO error-budget
burn.  The endpoints are the ones any scraping stack consumes — this tool
adds nothing the plane does not already export, it only renders it.

Usage::

    python tools/serve_top.py http://127.0.0.1:8080 [--interval 2]
        [--once] [--json]
    python tools/serve_top.py --pod http://h0:8080 http://h1:8080 ...

``--once`` renders a single frame and exits (0 = service reachable and
admitting, 3 = reachable but draining/stopped, 2 = unreachable) — the
scripting / smoke-test mode.  Without it the tool refreshes in place
(ANSI clear) every ``--interval`` seconds until interrupted.  ``--json``
emits the merged raw payloads instead of the rendered frame (``--once``
implied).

``--pod`` is the FEDERATED scrape: every given host's ``/metrics`` +
``/healthz`` in one frame — per-host state rows, the ``ncnet_serve_*``
counter families SUMMED across hosts, and the cumulative latency
histogram buckets merged by ``le`` edge so the p50/p95/p99 shown are the
POD's percentiles (bucket counts are additive; merged-then-interpolated
is exact at the bucket resolution, unlike averaging per-host
percentiles, which is wrong).  An unreachable host degrades to a named
row, never a crash; exit 0 only if every host is reachable and
admitting.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ncnet_tpu.observability.export import (  # noqa: E402
    histogram_percentile,
    parse_prometheus,
)


def _out(text: str) -> None:
    sys.stdout.write(text)


def fetch(base: str, timeout: float = 5.0
          ) -> Tuple[Optional[Dict[str, Any]], Optional[Dict[str, Any]],
                     Optional[str]]:
    """One poll: ``(health_doc, metric_families, error)``.  A 503 from
    ``/healthz`` is a VALID answer (a draining service reports itself);
    only transport failures return an error."""
    base = base.rstrip("/")
    try:
        try:
            with urllib.request.urlopen(base + "/healthz",
                                        timeout=timeout) as r:
                health = json.loads(r.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            if e.code != 503:
                raise
            health = json.loads(e.read().decode("utf-8"))
        with urllib.request.urlopen(base + "/metrics", timeout=timeout) as r:
            fams = parse_prometheus(r.read().decode("utf-8"))
    except Exception as e:  # noqa: BLE001 — every transport failure is
        # the same verdict: the plane is unreachable
        return None, None, f"{type(e).__name__}: {e}"
    return health, fams, None


def _bucket_latencies(fams: Dict[str, Any]) -> List[Dict[str, Any]]:
    fam = fams.get("ncnet_serve_latency_ms")
    if not fam:
        return []
    by_bucket: Dict[str, List] = {}
    for name, labels, value in fam["samples"]:
        if "bucket" in labels:
            by_bucket.setdefault(labels["bucket"], []).append(
                (name, labels, value))
    rows = []
    for bucket, samples in sorted(by_bucket.items()):
        n = next((v for nm, lb, v in samples if nm.endswith("_count")), 0)
        rows.append({
            "bucket": bucket, "n": int(n),
            "p50": histogram_percentile(samples, 50),
            "p95": histogram_percentile(samples, 95),
            "p99": histogram_percentile(samples, 99),
        })
    return rows


def render_frame(health: Dict[str, Any], fams: Dict[str, Any],
                 base: str) -> str:
    lines: List[str] = []
    add = lines.append
    svc = health.get("service", {})
    q = health.get("queue", {})
    pool = health.get("pool", {})
    add(f"ncnet serve_top — {base}  (healthz schema "
        f"{health.get('schema')})")
    add(f"state: {health.get('state')}  for {svc.get('age_s')}s"
        + (f"  reason: {svc.get('reason')}" if svc.get("reason") else ""))
    add(f"queue: {q.get('depth')}/{q.get('effective_max_queue')}  "
        f"inflight batches: {q.get('inflight_batches')}  "
        f"pipeline depth: {q.get('pipeline_depth')}  "
        f"replicas ready: {pool.get('ready')}/{pool.get('total')}")
    c = health.get("counters", {})
    add(f"outcomes: admitted={c.get('admitted')} results={c.get('results')}"
        f" deadline={c.get('deadline')} quarantined={c.get('quarantined')}"
        f" shed={c.get('shed')}")
    slo = health.get("slo")
    if slo and slo.get("admitted"):
        w = slo["window"]
        add(f"SLO burn: {slo['budget_burn_pct']}% of budget cumulative  |  "
            f"window({w['n']}): {w['burn_pct']}%  "
            f"[bad: {slo['bad']}]")
    st = health.get("store")
    if st:
        c = st.get("counters") or {}
        hp = st.get("hit_pct")
        add(f"store: {st.get('state')}"
            + (f" ({st.get('reason')})" if st.get("reason") else "")
            + f"  hit%: {f'{hp:.1f}' if hp is not None else '-'}"
            f"  entries: {st.get('entries')}"
            f"  {(st.get('bytes') or 0) / 2 ** 20:.1f} MiB"
            f"  corrupt: {c.get('corrupt', 0)}"
            f"  evict: {c.get('evictions', 0)}")
    add("")
    mem = health.get("memory") or {}
    hbm = mem.get("hbm") or {}
    add(f"{'replica':<8} {'state':<6} {'score':>10} {'ewma_ms':>9} "
        f"{'load':>4} {'batches':>8} {'fail':>5} {'deaths':>6} "
        f"{'dead_s':>7} {'hbm%':>6}")
    for r in pool.get("replicas", []):
        ewma = r.get("ewma_wall_ms")
        dead = r.get("dead_age_s")
        fill = (hbm.get(r["id"]) or {}).get("fill_pct")
        add(f"{r['id']:<8} {r['state']:<6} {r['score']:>10.4f} "
            f"{(f'{ewma:.2f}' if ewma is not None else '-'):>9} "
            f"{r['load']:>4} {r['batches']:>8} {r['failures']:>5} "
            f"{r['deaths']:>6} "
            f"{(f'{dead:.1f}' if dead is not None else '-'):>7} "
            f"{(f'{fill:.1f}' if fill is not None else '-'):>6}")
    pred = mem.get("predicted_ladder_bytes")
    if pred is not None or hbm:
        parts = []
        if pred is not None:
            parts.append(f"predicted ladder {pred / 2 ** 20:.1f} MiB "
                         f"({mem.get('ledger_programs')} warmed program(s))")
        head = mem.get("headroom_bytes")
        if head is not None:
            parts.append(f"headroom {head / 2 ** 20:.1f} MiB")
        peaks = [s.get("peak_bytes_in_use") for s in hbm.values()
                 if s.get("peak_bytes_in_use") is not None]
        if peaks:
            parts.append(f"peak in use {max(peaks) / 2 ** 20:.1f} MiB")
        if parts:
            add("")
            add("memory: " + "  ".join(parts))
    lat = _bucket_latencies(fams)
    if lat:
        add("")
        add(f"{'bucket':<16} {'n':>6} {'p50_ms':>9} {'p95_ms':>9} "
            f"{'p99_ms':>9}")
        for row in lat:
            fmt = lambda v: f"{v:.2f}" if v is not None else "-"  # noqa: E731
            add(f"{row['bucket']:<16} {row['n']:>6} {fmt(row['p50']):>9} "
                f"{fmt(row['p95']):>9} {fmt(row['p99']):>9}")
    act = health.get("activity")
    if act is not None:
        add("")
        add(f"activity: last dispatch/idle tick {act.get('age_s')}s ago  "
            f"({act.get('batches')} batches dispatched)")
    return "\n".join(lines) + "\n"


def merge_pod_metrics(per_host: List[Tuple[str, Optional[Dict[str, Any]]]]
                      ) -> Dict[str, Any]:
    """Sum the ``ncnet_serve_*`` families across hosts: counter/gauge
    samples add by (family, labels); histogram ``_bucket``/``_count``/
    ``_sum`` series are themselves cumulative counters, so the same
    summation merges the histograms exactly — ``histogram_percentile``
    over the merged buckets IS the pod percentile."""
    merged: Dict[str, Dict[str, Any]] = {}
    for _, fams in per_host:
        if not fams:
            continue
        for name, fam in fams.items():
            if not name.startswith("ncnet_serve_"):
                continue
            m = merged.setdefault(
                name, {"type": fam.get("type", "untyped"),
                       "help": fam.get("help", ""), "acc": {}})
            for sname, labels, value in fam["samples"]:
                key = (sname, tuple(sorted(labels.items())))
                m["acc"][key] = m["acc"].get(key, 0.0) + float(value)
    out: Dict[str, Any] = {}
    for name, m in merged.items():
        out[name] = {
            "type": m["type"], "help": m["help"],
            "samples": [(sname, dict(lbl), v)
                        for (sname, lbl), v in sorted(m["acc"].items())],
        }
    return out


def render_pod_frame(per_host, merged: Dict[str, Any]) -> str:
    """One federated frame: per-host state rows + pod-summed outcome
    counters + pod-merged latency percentiles."""
    lines: List[str] = []
    add = lines.append
    n_up = sum(1 for _, h, _, e in per_host if e is None)
    add(f"ncnet serve_top — POD of {len(per_host)} host(s), "
        f"{n_up} reachable")
    add(f"{'host':<28} {'state':<9} {'queue':>7} {'ready':>7} "
        f"{'results':>8} {'shed':>6}")
    for base, health, fams, err in per_host:
        if err is not None:
            add(f"{base:<28} {'UNREACH':<9} {'-':>7} {'-':>7} {'-':>8} "
                f"{'-':>6}  ({err})")
            continue
        q = health.get("queue", {})
        pool = health.get("pool", {})
        c = health.get("counters", {})
        ready = f"{pool.get('ready')}/{pool.get('total')}"
        add(f"{base:<28} {str(health.get('state')):<9} "
            f"{q.get('depth', '-'):>7} {ready:>7} "
            f"{c.get('results', '-'):>8} {c.get('shed', '-'):>6}")
    # pod-summed outcome counters from the merged families
    fam = merged.get("ncnet_serve_requests_total")
    if fam:
        totals: Dict[str, float] = {}
        for _, labels, v in fam["samples"]:
            key = labels.get("outcome", labels.get("state", "?"))
            totals[key] = totals.get(key, 0.0) + v
        add("")
        add("pod outcomes: " + "  ".join(
            f"{k}={int(v)}" for k, v in sorted(totals.items())))
    lat = _bucket_latencies(merged)
    if lat:
        add("")
        add(f"pod latency   {'bucket':<16} {'n':>6} {'p50_ms':>9} "
            f"{'p95_ms':>9} {'p99_ms':>9}")
        for row in lat:
            fmt = lambda v: f"{v:.2f}" if v is not None else "-"  # noqa: E731
            add(f"{'':<14}{row['bucket']:<16} {row['n']:>6} "
                f"{fmt(row['p50']):>9} {fmt(row['p95']):>9} "
                f"{fmt(row['p99']):>9}")
    return "\n".join(lines) + "\n"


def run_pod(urls: List[str], args) -> int:
    while True:
        per_host = []
        for u in urls:
            health, fams, err = fetch(u)
            per_host.append((u.rstrip("/"), health, fams, err))
        merged = merge_pod_metrics(
            [(b, f) for b, _, f, _ in per_host])
        if args.json:
            doc = {
                "hosts": {b: {"healthz": h, "error": e}
                          for b, h, _, e in per_host},
                "pod_metrics": {name: fam["samples"]
                                for name, fam in sorted(merged.items())},
            }
            _out(json.dumps(doc, indent=2, sort_keys=True) + "\n")
        else:
            frame = render_pod_frame(per_host, merged)
            if not args.once:
                _out("\x1b[2J\x1b[H")
            _out(frame)
        if args.once or args.json:
            if any(e is not None for _, _, _, e in per_host):
                return 2
            return 0 if all(
                h.get("state") in ("STARTING", "READY", "DEGRADED")
                for _, h, _, _ in per_host) else 3
        sys.stdout.flush()
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Live console over a match service's /metrics + "
                    "/healthz introspection plane")
    ap.add_argument("url", nargs="?", default=None,
                    help="base URL of the introspection endpoint "
                         "(e.g. http://127.0.0.1:8080)")
    ap.add_argument("--pod", nargs="+", metavar="URL", default=None,
                    help="federated mode: scrape EVERY given host, sum "
                         "the ncnet_serve_* counters and merge the "
                         "cumulative latency buckets into pod "
                         "p50/p95/p99 (one frame for the whole pod)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period in seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (scripting mode): "
                         "0 admitting, 3 draining/stopped, 2 unreachable")
    ap.add_argument("--json", action="store_true",
                    help="emit the merged raw payloads as one JSON "
                         "document (implies --once)")
    args = ap.parse_args(argv)
    if args.pod:
        return run_pod(args.pod, args)
    if not args.url:
        ap.error("a URL is required (or use --pod url1 url2 ...)")

    while True:
        health, fams, err = fetch(args.url)
        if err is not None:
            _out(f"unreachable: {args.url} ({err})\n")
            if args.once or args.json:
                return 2
        elif args.json:
            doc = {"healthz": health,
                   "metrics": {name: fam["samples"]
                               for name, fam in sorted(fams.items())}}
            _out(json.dumps(doc, indent=2, sort_keys=True) + "\n")
            return 0 if health.get("state") in (
                "STARTING", "READY", "DEGRADED") else 3
        else:
            frame = render_frame(health, fams, args.url)
            if not args.once:
                _out("\x1b[2J\x1b[H")  # clear + home: refresh in place
            _out(frame)
            if args.once:
                return 0 if health.get("state") in (
                    "STARTING", "READY", "DEGRADED") else 3
        sys.stdout.flush()
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except KeyboardInterrupt:
        raise SystemExit(0)
