#!/usr/bin/env python
"""Run ONE retrieval shard host: a ``ShardService`` over a coarse-volume
store + index, behind the ``/healthz``/``/metrics`` control plane and
``POST /retrieve`` wire data plane (``ncnet_tpu/retrieval/``).

This is the process a ``RetrievalCoordinator`` scatters to — and the
process the retrieval chaos suite (tests/test_retrieval.py) SIGKILLs
mid-sweep to prove replication turns shard death into lost capacity, not
lost coverage.  Same lifecycle contract as ``serve_backend.py``:

  * on start it prints exactly ONE JSON line to stdout —
    ``{"url": ..., "pid": ..., "shard": ..., "assigned": ...}`` — and
    nothing else;
  * SIGTERM begins the coordinated drain: ``/healthz`` answers 503 so the
    coordinator demotes this host BEFORE it goes away; exits 0 STOPPED;
  * a fixed ``--port`` supports restart-in-place (a supervisor reviving a
    killed shard at the same address, which the coordinator's
    resurrection probes then re-admit).

The shard derives WHAT it serves from the index manifest + the rendezvous
assignment over ``--shards`` — no placement file, so every host spawned
with the same arguments agrees with the coordinator by construction.

Usage::

    python tools/serve_shard.py --shard-id s0 --shards s0,s1,s2,s3
        --store /path/to/store --index coarse_index*.json
        [--replication 2] [--topk 10] [--port 0] [--events ev.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="One retrieval shard host: ShardService + /healthz "
                    "control plane + /retrieve wire data plane")
    ap.add_argument("--shard-id", required=True)
    ap.add_argument("--shards", required=True,
                    help="comma-separated ids of the WHOLE shard set "
                         "(assignment is a pure function of this list)")
    ap.add_argument("--store", required=True,
                    help="feature-store root holding the coarse entries")
    ap.add_argument("--index", required=True,
                    help="coarse index manifest path or glob "
                         "(build_coarse_index.py output)")
    ap.add_argument("--replication", type=int, default=2)
    ap.add_argument("--topk", type=int, default=10)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 = ephemeral (printed in the startup line); "
                         "fixed for the restart-in-place shape")
    ap.add_argument("--events", default=None,
                    help="bind this host's event log here (torn-tail "
                         "tolerant across SIGKILL)")
    args = ap.parse_args(argv)

    from ncnet_tpu.observability import events as obs_events
    from ncnet_tpu.retrieval import ShardService, load_index_manifests
    from ncnet_tpu.store import FeatureStore

    if args.events:
        from ncnet_tpu.observability import EventLog

        obs_events.set_global_sink(EventLog(args.events))

    shard_ids = [s for s in (t.strip() for t in args.shards.split(","))
                 if s]
    try:
        index = load_index_manifests(args.index)
    except (OSError, ValueError) as e:
        print(json.dumps({"error": f"index load failed: {e}"}), flush=True)
        return 1
    store = FeatureStore(args.store, index["fingerprint"], scope="coarse")
    service = ShardService(
        args.shard_id, shard_ids, index, store,
        replication=args.replication, default_topk=args.topk,
        introspect_host=args.host, introspect_port=args.port)
    service.start()
    if service.introspect_url is None:
        print(json.dumps({"error": f"failed to bind {args.host}:"
                          f"{args.port}"}), flush=True)
        service.stop()
        return 1

    def _sigterm(signum, frame):
        service.request_drain("sigterm")

    signal.signal(signal.SIGTERM, _sigterm)
    print(json.dumps({"url": service.introspect_url, "pid": os.getpid(),
                      "shard": service.shard_id,
                      "assigned": len(service.assigned)}), flush=True)
    try:
        while service.state not in ("STOPPED",):
            time.sleep(0.1)
            if service.state == "DRAINING":
                # give in-flight sweeps a beat to finish, then stop: the
                # coordinator has already demoted us off its scatter plan
                time.sleep(0.2)
                service.stop()
    except KeyboardInterrupt:
        service.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
