#!/usr/bin/env python
"""Exercise REAL runtime tier demotion on a TPU backend.

The tier-1 tests prove the demote-retrace-retry machinery with an INJECTED
device error on CPU (where every tier resolves to XLA anyway).  This probe is
for the next TPU-attached session: it runs the InLoc-shaped forward at a
resident-eligible shape, confirms which tier ``choose_fused_stack`` picks,
then demotes tiers one at a time and verifies (1) the re-traced program
really lands on the next tier, (2) outputs stay parity-correct across tiers
(the guarantee the eval loops' mid-run recovery relies on), and (3) an
injected dispatch failure routed through ``recover_from_device_failure``
produces the same demotion end-to-end.

Usage: python tools/eval_faults_probe.py [side]

(side: square volume side, default 25 — the PF-Pascal shape class; the
InLoc rectangular class is covered by the resident kernel's own probes,
tools/nc_resident_probe.py.)
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")

S = int(sys.argv[1]) if len(sys.argv) > 1 else 25
DT = jnp.bfloat16


def make_params(key):
    params = []
    for (ci, co) in [(1, 16), (16, 16), (16, 1)]:
        key, k1, k2 = jax.random.split(key, 3)
        params.append({
            "w": jax.random.normal(k1, (5, 5, 5, 5, ci, co), DT) * 0.05,
            "b": jax.random.normal(k2, (co,), DT) * 0.1,
        })
    return params


def main():
    from ncnet_tpu.models.ncnet import (
        ResilientJit,
        recover_from_device_failure,
    )
    from ncnet_tpu.ops import (
        choose_fused_stack,
        demoted_fused_tiers,
        nc_stack_fused,
        reset_fused_tier_demotions,
    )
    from ncnet_tpu.utils import faults

    print(f"device={jax.devices()[0].device_kind} S={S}")
    params = make_params(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (1, S, S, S, S, 1), DT)
    kernels, channels = (5, 5, 5), (16, 16, 1)

    # the counter increments at TRACE time: after every retrace it must
    # move, or the demotion path is replaying a stale cached executable and
    # the "tier" printed below is a lie (the jit identity-cache trap)
    traces = [0]

    def body(p, v):
        traces[0] += 1
        return nc_stack_fused(p, v)

    fwd = ResilientJit(body, label="probe")

    def tier():
        return choose_fused_stack(S, S, S, S, kernels, channels)

    reset_fused_tier_demotions()
    outputs = {}
    seen = []
    # walk the ladder: whatever tier the chooser picks now, demote it, and
    # confirm the re-traced program still agrees numerically
    from ncnet_tpu.ops import demote_fused_tier

    while True:
        t = tier() or "xla"
        seen.append(t)
        n_traces = traces[0]
        outputs[t] = np.asarray(fwd(params, x), np.float32)
        assert traces[0] == n_traces + 1, (
            "dispatch replayed a stale cached executable — retrace() did "
            "not actually re-trace; the printed tier is not what ran"
        )
        print(f"tier={t}: ran OK "
              f"(demoted so far: {sorted(demoted_fused_tiers())})")
        if t == "xla":
            break
        demote_fused_tier(t)
        fwd.retrace()
    print("tier ladder:", " -> ".join(seen))
    ref = outputs["xla"]
    for t, out in outputs.items():
        err = float(np.max(np.abs(out - ref)))
        print(f"parity {t} vs xla: max|diff|={err:.3e}")
        assert err < 0.1, f"tier {t} diverged from XLA"

    # end-to-end: an injected dispatch failure routed through the production
    # recovery demotes exactly one tier and the retry completes
    reset_fused_tier_demotions()
    fwd.retrace()
    start = tier() or "xla"
    faults.install(faults.FaultPlan(device_fail_calls=(2,)))
    try:
        fwd(params, x)  # call 1: fine
        try:
            fwd(params, x)  # call 2: injected failure
            raise AssertionError("injected device error did not fire")
        except faults.InjectedDeviceError as e:
            demoted = recover_from_device_failure(e, fwd)
        out = np.asarray(fwd(params, x), np.float32)  # call 3: next tier
    finally:
        faults.clear()
        reset_fused_tier_demotions()
    err = float(np.max(np.abs(out - ref)))
    print(f"recovery: started on '{start}', demoted '{demoted}', "
          f"retry completed with max|diff|={err:.3e} vs xla")
    assert start == "xla" or demoted == start
    print("OK")


if __name__ == "__main__":
    main()
