#!/usr/bin/env python
"""Open-loop streaming probe of the tracked match mode (serving/stream.py).

For the next TPU-attached session — the streaming twin of
``serve_probe``.  Drives N concurrent camera streams of jittered/bursty
frames through ``MatchService.stream_submit`` against a tracking-feasible
bucket, injects one scene cut per stream, and reports what the CPU tier-1
suite can only smoke:

  1. **Steady-frame walls** — latency percentiles of the TRACKED path
     (temporal candidates, coarse pass skipped, reference features
     resolved once per stream) vs the per-frame coarse-to-fine wall at
     the SAME shape: the headline of ISSUE 19.
  2. **Cut recovery** — the injected cut's fallback-frame wall (the exact
     coarse-to-fine re-seed) and the first tracked frame after it.
  3. **Skip accounting** — the coarse-skip fraction, the engine's
     ``coarse_passes`` spy delta over the steady segment (must be ZERO),
     and the stream-session digest/feature-cache effectiveness.
  4. **Replayability** — per-stream seq ordering and the frame-outcome
     identity (frames == tracked + fallback + cold) recomputed from the
     event log alone, the ``run_report`` discipline.

Usage::

    python tools/stream_probe.py [--tiny] [--streams 2] [--frames 14]
        [--rate 8.0] [--side 192] [--json out.json]

``--tiny`` runs the CPU-sized smoke configuration (tiny backbone, 96 px)
— the tier-1 smoke of the streaming plane's plumbing.  Output: one JSON
document (stdout, plus ``--json`` path).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from typing import Any, Dict, List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _percentiles(xs: List[float]) -> Dict[str, float]:
    import numpy as np

    if not xs:
        return {}
    return {
        "p50": round(float(np.percentile(xs, 50)), 3),
        "p95": round(float(np.percentile(xs, 95)), 3),
        "p99": round(float(np.percentile(xs, 99)), 3),
        "mean": round(float(np.mean(xs)), 3),
        "n": len(xs),
    }


def probe(tiny: bool = False, streams: int = 2, frames: int = 14,
          rate_hz: float = 8.0, side: int = 192,
          events_path: str = "") -> Dict[str, Any]:
    import warnings

    import jax
    import numpy as np

    from ncnet_tpu import models
    from ncnet_tpu.config import ModelConfig
    from ncnet_tpu.observability import EventLog
    from ncnet_tpu.observability import events as obs_events
    from ncnet_tpu.serving import MatchService, ServingConfig
    from ncnet_tpu.serving.stream import run_stream_load

    if tiny:
        cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                          ncons_channels=(1,), sparse_topk=4,
                          sparse_factor=2)
        side = min(side, 96)
    else:
        cfg = ModelConfig(ncons_kernel_sizes=(5, 5, 5),
                          ncons_channels=(16, 16, 1),
                          half_precision=True, backbone_bf16=True,
                          sparse_topk=4, sparse_factor=2)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # random-trunk warning: timing only
        params = models.init_ncnet(cfg, jax.random.key(0))

    scfg = ServingConfig(
        max_queue=128, max_batch=4, max_in_flight_per_client=256,
        buckets=((side, side),), max_buckets=2,
        warm_buckets=((side, side),), slo_ms=10_000.0)
    events_path = events_path or os.path.join(
        tempfile.mkdtemp(prefix="stream_probe_"), "events.jsonl")

    cut_at = max(frames * 2 // 3, 2)
    rng = np.random.default_rng(23)
    refs = [rng.integers(0, 255, (side, side, 3), dtype=np.uint8)
            for _ in range(streams)]
    # pre-generated (frame_fn runs on per-stream threads): small jitter
    # around the reference = steady frame, one unrelated image = the cut
    tgts = [[(rng.integers(0, 255, (side, side, 3), dtype=np.uint8)
              if fi == cut_at else
              np.clip(refs[si].astype(np.int16)
                      + rng.integers(-3, 4, refs[si].shape),
                      0, 255).astype(np.uint8))
             for fi in range(frames)]
            for si in range(streams)]

    out: Dict[str, Any] = {
        "device": str(jax.devices()[0].device_kind),
        "tiny": tiny, "side": side,
        "streams": streams, "frames_per_stream": frames,
        "rate_hz": rate_hz, "cut_at": cut_at,
        "events_path": events_path,
    }
    with obs_events.bound(EventLog(events_path)):
        service = MatchService(cfg, params, scfg).start()
        try:
            eng = service._pool.replicas[0].engine
            out["tracking_feasible"] = bool(
                eng.tracking_feasible((side, side), (side, side)))
            # one cold frame per stream, then spy-count the steady segment
            for si in range(streams):
                service.stream_submit(f"cam{si}", refs[si], tgts[si][0])
            cp0 = eng.coarse_passes
            recs = run_stream_load(
                service, lambda si, fi: (refs[si], tgts[si][fi + 1]),
                streams=streams, frames=frames - 1, rate_hz=rate_hz,
                jitter=0.3, burst_every=4, seed=23)
            served = [r for r in recs if r["outcome"] == "result"]
            steady = [r["wall_ms"] for r in served
                      if r["tracked"] and not r["fallback"]]
            cuts = [r["wall_ms"] for r in served if r["fallback"]]
            out["steady_wall_ms"] = _percentiles(steady)
            out["cut_recovery_ms"] = _percentiles(cuts)
            out["coarse_skip_pct"] = round(
                100.0 * len(steady) / max(len(served), 1), 2)
            # fallback frames + any post-cut re-seed pay exactly one
            # coarse pass each; steady tracked frames pay zero
            out["coarse_passes_steady_delta"] = eng.coarse_passes - cp0
            out["expected_coarse_passes"] = len(served) - len(steady)
            out["tracked_dispatches"] = eng.tracked_dispatches
            out["recall"] = _percentiles(
                [r["recall"] for r in served if r["recall"] is not None])
            # the reference: the SAME pairs through the plain per-frame
            # coarse-to-fine path
            c2f = []
            for i in range(6):
                r = service.submit(
                    refs[i % streams],
                    tgts[i % streams][1 + i % (cut_at - 1)]
                ).result(timeout=600)
                c2f.append(r.wall_s * 1e3)
            out["c2f_frame_ms"] = _percentiles(c2f)
            out["steady_below_c2f"] = bool(
                steady and out["steady_wall_ms"]["p50"]
                < out["c2f_frame_ms"]["p50"])
            doc = service.health()
            out["streams_doc"] = {
                k: doc["streams"][k]
                for k in ("frames", "tracked_frames", "fallback_frames",
                          "cold_frames", "active")}
            out["slo_budget_burn_pct"] = doc["slo"]["budget_burn_pct"]
        finally:
            service.stop()

    # replay: ordering + the frame-outcome identity from the log alone
    _, events = obs_events.replay_events(events_path)
    frames_ev = [e for e in events if e.get("event") == "stream_frame"]
    per: Dict[str, List[int]] = {}
    for e in frames_ev:
        per.setdefault(e["stream"], []).append(e["seq"])
    out["replay_ordering_ok"] = all(
        seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        for seqs in per.values())
    kinds = [e.get("kind") for e in frames_ev]
    out["replay_outcome_identity_ok"] = (
        len(frames_ev)
        == kinds.count("tracked") + kinds.count("fallback")
        + kinds.count("cold"))
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tiny", action="store_true",
                    help="CPU-sized smoke configuration (tiny trunk, 96px)")
    ap.add_argument("--streams", type=int, default=2)
    ap.add_argument("--frames", type=int, default=14)
    ap.add_argument("--rate", type=float, default=8.0)
    ap.add_argument("--side", type=int, default=192)
    ap.add_argument("--json", default="", help="also write the document here")
    args = ap.parse_args()

    doc = probe(tiny=args.tiny, streams=args.streams, frames=args.frames,
                rate_hz=args.rate, side=args.side)
    text = json.dumps(doc, indent=2, sort_keys=True)
    print(text)
    if args.json:
        with open(args.json, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
