#!/usr/bin/env python
"""Per-layer conv4d variant timings for the production NC stacks.

PF-Pascal arch: kernels (5,5,5), channels 1->16->16->1.
Measures every (layer, variant) standalone plus the composed symmetric stack,
to separate per-layer cost from composition (relayout) overhead.

Usage: python tools/xla_layer_probe.py [batch]
"""

import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")

from _timing import timeit  # noqa: E402

B = int(sys.argv[1]) if len(sys.argv) > 1 else 4
S = 25
DT = jnp.bfloat16




def chain(op):
    def step(carry):
        x, w = carry
        out = op(x, w)
        eps = (jnp.sum(out.astype(jnp.float32)) * 1e-12).astype(x.dtype)
        return x + eps, w - eps
    return step


def layer_input(cin, cout, k):
    def make(key):
        k1, k2 = jax.random.split(key)
        return (
            jax.random.normal(k1, (B, S, S, S, S, cin), DT) * 0.03,
            jax.random.normal(k2, (k,) * 4 + (cin, cout), DT) * 0.05,
        )
    return make


def main():
    from ncnet_tpu.ops.conv4d import conv4d

    print(f"device={jax.devices()[0].device_kind} batch={B} dtype=bf16")
    layers = [("1to16", 1, 16, 5), ("16to16", 16, 16, 5), ("16to1", 16, 1, 5)]
    variants = ("unroll", "tapfold", "coutfold", "afold")
    for name, cin, cout, k in layers:
        row = []
        for v in variants:
            try:
                ms = timeit(
                    chain(lambda x, w, v=v: conv4d(x, w, variant=v)),
                    layer_input(cin, cout, k), per=B, n_long=8,
                )
                row.append(f"{v}={ms:6.3f}")
            except Exception as e:
                row.append(f"{v}=ERR({str(e)[:40]})")
        print(f"{name:>7}: " + "  ".join(row))

    # composed stacks: auto per layer, then the production symmetric path
    from ncnet_tpu.models.ncnet import neigh_consensus

    def stack_input(key):
        k1, *ks = jax.random.split(key, 4)
        corr = jax.random.normal(k1, (B, S, S, S, S), DT) * 0.03
        chans = [(1, 16), (16, 16), (16, 1)]
        params = []
        for kk, (ci, co) in zip(ks, chans):
            params.append({
                "w": jax.random.normal(kk, (5, 5, 5, 5, ci, co), DT) * 0.05,
                "b": jnp.zeros((co,), DT),
            })
        return corr, params

    def sym_step(carry):
        corr, params = carry
        out = neigh_consensus(params, corr, symmetric=True)
        eps = (jnp.sum(out.astype(jnp.float32)) * 1e-12).astype(corr.dtype)
        return corr + eps, params

    print(f"  stack symmetric (production): "
          f"{timeit(sym_step, stack_input, per=B, n_long=8):6.3f} ms/pair")

    def asym_step(carry):
        corr, params = carry
        out = neigh_consensus(params, corr, symmetric=False)
        eps = (jnp.sum(out.astype(jnp.float32)) * 1e-12).astype(corr.dtype)
        return corr + eps, params

    print(f"  stack one-pass (no symmetry): "
          f"{timeit(asym_step, stack_input, per=B, n_long=8):6.3f} ms/pair")


if __name__ == "__main__":
    main()
