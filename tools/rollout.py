#!/usr/bin/env python
"""Kick and watch a live model rollout over the serving control plane.

The operator-facing half of ``ncnet_tpu/serving/rollout.py``: one
invocation POSTs ``{"checkpoint": ...}`` to a serving host's
``/rollout`` endpoint (``serving/introspect.py`` — the same wire plane
``POST /match`` rides), then polls ``GET /rollout`` until the state
machine reaches a terminal phase, printing each phase transition as it is
observed.  The whole exchange is plain HTTP against the introspection
port, so the tool runs from ANY host that can reach the pod — no shared
filesystem, no in-process access.

The candidate checkpoint path is resolved ON THE SERVING HOST (PR 1's
newest-complete resolution), so pass a path meaningful there.  Judge
knobs (canary fraction, PSI threshold, ...) ride along in the same JSON
body; unset knobs take the ``RolloutConfig`` defaults.

``--watch`` skips the POST and just follows whatever rollout is already
in flight — the second-operator shape, and the recovery shape after this
tool (not the rollout) died mid-watch.

Exit codes mirror the terminal phase so supervisors can script on them:

  * 0 — COMPLETE (promoted; the pod converged on the new version);
  * 2 — ROLLED_BACK (the canary judge or a swap failure auto-rolled the
    pod back to the old version — the pod is consistent, the CANDIDATE
    is what needs attention);
  * 1 — anything else: refusal at staging (the ``IDLE`` terminal, e.g.
    checksum/arch mismatch), an unreachable host, a 4xx/5xx answer, or
    the poll timeout expiring with the rollout still in flight (the
    rollout itself keeps running server-side; re-attach with --watch).

Usage::

    python tools/rollout.py http://host:port ckpts/run42 \
        [--canary-fraction 0.25] [--canary-min-results 16]
        [--psi-threshold 0.25] [--state-path /path/state.json]
        [--poll 0.5] [--timeout 600] [--watch] [--json]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Optional, Tuple

TERMINAL_PHASES = ("COMPLETE", "ROLLED_BACK", "IDLE")


def _out(line: str) -> None:
    # this tool's stdout IS its interface (the no-bare-print pin covers
    # it): one timeline line per observed transition, flushed so a
    # supervisor tailing the pipe sees phases as they happen
    sys.stdout.write(line + "\n")
    sys.stdout.flush()


def _get(url: str, timeout: float) -> Dict[str, Any]:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


def post_rollout(base: str, checkpoint: str, knobs: Dict[str, Any],
                 timeout: float = 10.0) -> Tuple[int, Dict[str, Any]]:
    """``POST /rollout``; returns ``(http_status, parsed_or_error_doc)``.
    202 carries the controller's first status snapshot; 4xx/5xx carry
    ``{"error": <the server's plain-text answer>}``.  The body carries a
    fresh pod trace (additive ``trace`` key — old hosts ignore it), so
    the operator's rollout order shows up in the federated pod trace."""
    try:  # best-effort: the tool must work without the package on path
        from ncnet_tpu.observability.tracing import new_trace
        tr = {"trace": new_trace().to_header()}
    except ImportError:
        tr = {}
    body = json.dumps({"checkpoint": checkpoint, **tr,
                       **knobs}).encode("utf-8")
    req = urllib.request.Request(
        base + "/rollout", data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        return e.code, {"error": e.read().decode("utf-8",
                                                 "replace").strip()}


def watch(base: str, poll_s: float, timeout_s: float,
          http_timeout: float = 10.0) -> Optional[Dict[str, Any]]:
    """Poll ``GET /rollout`` until a terminal phase, printing transitions.
    Returns the final status document, or None when the deadline expired
    with the rollout still in flight (it keeps running server-side)."""
    deadline = time.monotonic() + timeout_s
    last_phase = None
    while True:
        try:
            st = _get(base + "/rollout", timeout=http_timeout)
        except Exception as e:  # noqa: BLE001 — a transient poll failure
            # must not abandon a healthy rollout; the deadline bounds it
            _out(f"  (poll failed: {type(e).__name__}: {e})")
            st = None
        if st is not None:
            phase = st.get("phase")
            if phase != last_phase:
                vers = ""
                if st.get("old_version") or st.get("new_version"):
                    vers = (f"  [{st.get('old_version')} -> "
                            f"{st.get('new_version')}]")
                reason = st.get("reason")
                _out(f"-> {phase}{vers}"
                     + (f"  ({reason})" if reason else ""))
                last_phase = phase
            # IDLE is terminal only as a refusal (reason set) or when no
            # controller was ever attached ("candidate" absent): a just-
            # POSTed rollout reads IDLE for an instant before STAGING
            if phase in TERMINAL_PHASES and (
                    phase != "IDLE" or st.get("reason")
                    or "candidate" not in st):
                return st
        if time.monotonic() >= deadline:
            return None
        time.sleep(poll_s)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Kick a canaried live weight rollout on a serving "
                    "host (POST /rollout) and follow it to its terminal "
                    "phase (exit 0=COMPLETE, 2=ROLLED_BACK, 1=refused/"
                    "error/timeout)")
    ap.add_argument("url", help="serving host base URL (the "
                                "introspection port)")
    ap.add_argument("checkpoint", nargs="?", default=None,
                    help="candidate checkpoint dir or versioned root, as "
                         "seen from the SERVING host (omit with --watch)")
    ap.add_argument("--watch", action="store_true",
                    help="don't POST — follow the rollout already in "
                         "flight (also the recovery path when a previous "
                         "invocation died mid-watch)")
    ap.add_argument("--canary-fraction", type=float, default=None)
    ap.add_argument("--canary-min-results", type=int, default=None)
    ap.add_argument("--canary-timeout-s", type=float, default=None)
    ap.add_argument("--drain-timeout-s", type=float, default=None)
    ap.add_argument("--psi-threshold", type=float, default=None)
    ap.add_argument("--error-rate-margin", type=float, default=None)
    ap.add_argument("--latency-factor", type=float, default=None)
    ap.add_argument("--min-latency-samples", type=int, default=None)
    ap.add_argument("--gc-keep-generations", type=int, default=None)
    ap.add_argument("--state-path", default=None,
                    help="durable version-pointer file on the serving "
                         "host (crash recovery reads it at restart)")
    ap.add_argument("--poll", type=float, default=0.5,
                    help="poll period in seconds (default 0.5)")
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="give up watching after this many seconds "
                         "(default 600; the rollout keeps running "
                         "server-side — re-attach with --watch)")
    ap.add_argument("--json", action="store_true",
                    help="also emit the final status as one JSON doc")
    args = ap.parse_args(argv)
    base = args.url.rstrip("/")

    if not args.watch:
        if args.checkpoint is None:
            ap.error("a checkpoint is required unless --watch is given")
        knobs = {
            name: getattr(args, name)
            for name in ("canary_fraction", "canary_min_results",
                         "canary_timeout_s", "drain_timeout_s",
                         "psi_threshold", "error_rate_margin",
                         "latency_factor", "min_latency_samples",
                         "state_path", "gc_keep_generations")
            if getattr(args, name) is not None
        }
        try:
            code, doc = post_rollout(base, args.checkpoint, knobs)
        except Exception as e:  # noqa: BLE001 — unreachable host etc.
            _out(f"rollout request failed: {type(e).__name__}: {e}")
            return 1
        if code != 202:
            _out(f"rollout refused by {base} (HTTP {code}): "
                 f"{doc.get('error', doc)}")
            return 1
        _out(f"rollout accepted by {base}: candidate "
             f"{args.checkpoint!r}")

    st = watch(base, args.poll, args.timeout)
    if st is None:
        _out(f"gave up after {args.timeout}s with the rollout still in "
             "flight (it keeps running server-side; re-attach with "
             "--watch)")
        return 1
    if args.json:
        _out(json.dumps(st, indent=2, sort_keys=True))
    phase = st.get("phase")
    if phase == "COMPLETE":
        _out(f"COMPLETE: pod converged on {st.get('new_version')}")
        return 0
    if phase == "ROLLED_BACK":
        _out(f"ROLLED_BACK ({st.get('reason')}): pod restored to "
             f"{st.get('old_version')} — the pod is consistent; the "
             "candidate is what needs attention")
        return 2
    _out(f"terminal phase {phase} ({st.get('reason')})")
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
