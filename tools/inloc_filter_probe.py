#!/usr/bin/env python
"""Per-variant conv4d timings at the InLoc volume scale (56M cells, IVD
arch 1->16 k3 + 16->1 k3, bf16), plus maxpool4d / mutual_matching at scale.

CAUTION (measured, twice): standalone wins here do NOT transfer — swapping
the 1->16 layer to the standalone-3x-faster coutfold made the COMPOSED
ncnet_filter slower (88.3 -> 99.0 ms).  Treat these numbers as hypotheses
for composed A/B runs only (ops/conv4d.py choose_conv4d_variant records the
history).

Usage: python tools/inloc_filter_probe.py
"""

import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")

from _timing import timeit  # noqa: E402

from ncnet_tpu.ops import maxpool4d_with_argmax, mutual_matching  # noqa: E402
from ncnet_tpu.ops.conv4d import conv4d  # noqa: E402
from ncnet_tpu.ops.correlation import correlation_4d  # noqa: E402

# fine and pooled InLoc volumes (query 3200x2400, db 1200x1600 -> 2400x3200)
FQ = (200, 150)
FD = (150, 200)
PQ = (100, 75)
PD = (75, 100)
DT = jnp.bfloat16


def chain(op):
    def step(carry):
        x, w = carry
        out = op(x, w)
        eps = (jnp.sum(out.astype(jnp.float32)) * 1e-12).astype(x.dtype)
        return x + eps, w
    return step


def layer_input(cin, cout, k):
    def make(key):
        k1, k2 = jax.random.split(key)
        return (
            jax.random.normal(k1, (1, *PQ, *PD, cin), DT) * 0.1,
            jax.random.normal(k2, (k,) * 4 + (cin, cout), DT) * 0.1,
        )
    return make


def corr_born_volume(key, fine):
    """A volume BORN from the correlation einsum — a raw random volume makes
    XLA pick a pathological 66x-padded layout for maxpool4d's 8D reshape
    (tools/_timing.py docstring)."""
    k1, k2 = jax.random.split(key)
    shape = (FQ, FD) if fine else (PQ, PD)
    fa = jax.random.normal(k1, (1, *shape[0], 8), DT) * 0.2
    fb = jax.random.normal(k2, (1, *shape[1], 8), DT) * 0.2
    return fa, fb


def main():
    print(f"device={jax.devices()[0].device_kind} pooled {PQ}x{PD} bf16")
    for name, cin, cout in (("1to16_k3", 1, 16), ("16to1_k3", 16, 1)):
        row = []
        for v in ("auto", "unroll", "tapfold", "coutfold"):
            try:
                ms = timeit(
                    chain(lambda x, w, v=v: conv4d(x, w, variant=v)),
                    layer_input(cin, cout, 3), n_long=4,
                )
                row.append(f"{v}={ms:6.1f}")
            except Exception as e:
                row.append(f"{v}=ERR({str(e)[:30]})")
        print(f"{name}: " + "  ".join(row))

    def pool_step(carry):
        fa, fb = carry
        pooled, delta = maxpool4d_with_argmax(correlation_4d(fa, fb), 2)
        eps = jnp.sum(pooled.astype(jnp.float32)) * 1e-12
        for d in delta:
            eps = eps + jnp.sum(d.astype(jnp.float32)) * 1e-12
        return fa + eps.astype(fa.dtype), fb

    print("corr+maxpool4d_k2_fine: "
          f"{timeit(pool_step, lambda k: corr_born_volume(k, True), n_long=4):.1f} ms")

    def mm_step(carry):
        fa, fb = carry
        out = mutual_matching(correlation_4d(fa, fb))
        eps = (jnp.sum(out.astype(jnp.float32)) * 1e-12).astype(fa.dtype)
        return fa + eps, fb

    print("corr+mutual_matching_pooled: "
          f"{timeit(mm_step, lambda k: corr_born_volume(k, False), n_long=4):.1f} ms")


if __name__ == "__main__":
    main()
