#!/usr/bin/env python
"""Composed-filter stage breakdown at the PF-Pascal bench shape.

The bench's ``filter_ms_per_pair_bf16`` (7.93 r4) sits 5.5x above its MXU
bound; before building a fused kernel, measure WHERE the time goes — in
composition, not standalone (standalone wins have twice inverted composed,
see ops/conv4d.py history).  Prefix-differencing: time the composed filter
truncated after each stage (volume born from the production einsum, every
output consumed); consecutive differences are the composed per-stage costs.

Stages mirror ncnet_filter + the batch-folded symmetric stack
(models/ncnet.py): MM -> [fold 2B] L1 -> L2 -> L3 -> [unfold+add] -> MM.

Usage: python tools/filter_stage_probe.py [batch]
"""

import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")

from _timing import timeit  # noqa: E402

B = int(sys.argv[1]) if len(sys.argv) > 1 else 4
IMG_FEAT = 25
DT = jnp.bfloat16


def make_input(key):
    k1, k2, *ks = jax.random.split(key, 5)
    feat = (IMG_FEAT, IMG_FEAT)
    fa = jax.random.normal(k1, (B, *feat, 128), jnp.float32) * 0.03
    fb = jax.random.normal(k2, (B, *feat, 128), jnp.float32) * 0.03
    chans = [(1, 16), (16, 16), (16, 1)]
    params = []
    for kk, (ci, co) in zip(ks, chans):
        params.append({
            "w": jax.random.normal(kk, (5, 5, 5, 5, ci, co), DT) * 0.05,
            "b": jnp.zeros((co,), DT),
        })
    return fa, fb, params


def make_prefix(n_stages):
    """Composed filter truncated after stage n (1=corr, 2=+MM, 3=+fold+L1,
    4=+L2, 5=+L3, 6=+unfold/add, 7=+final MM)."""
    from ncnet_tpu.ops import correlation_4d, mutual_matching
    from ncnet_tpu.ops.conv4d import conv4d

    def step(carry):
        fa, fb, params = carry
        x = correlation_4d(fa.astype(DT), fb.astype(DT))
        if n_stages >= 2:
            x = mutual_matching(x)
        if n_stages >= 3:
            x = x[..., None]
            xt = jnp.transpose(x, (0, 3, 4, 1, 2, 5))
            x = jnp.concatenate([x, xt], axis=0)  # batch-fold: 2B volumes
            x = jax.nn.relu(conv4d(x, params[0]["w"], params[0]["b"]))
        if n_stages >= 4:
            x = jax.nn.relu(conv4d(x, params[1]["w"], params[1]["b"]))
        if n_stages >= 5:
            x = jax.nn.relu(conv4d(x, params[2]["w"], params[2]["b"]))
        if n_stages >= 6:
            y = x[..., 0]
            x = y[:B] + jnp.transpose(y[B:], (0, 3, 4, 1, 2))
        if n_stages >= 7:
            x = mutual_matching(x)
        eps = (jnp.sum(x.astype(jnp.float32)) * 1e-12).astype(fa.dtype)
        return fa + eps, fb, params

    return step


NAMES = ["corr", "+mm1", "+fold+L1", "+L2", "+L3", "+unfold", "+mm2"]


def main():
    print(f"device={jax.devices()[0].device_kind} batch={B} dtype=bf16 "
          f"(symmetric batch-fold: convs see batch {2 * B})")
    prev = 0.0
    for n in range(1, 8):
        ms = timeit(make_prefix(n), make_input, per=B, n_long=8)
        print(f"prefix {n} ({NAMES[n-1]:>9}): {ms:7.3f} ms/pair   "
              f"delta {ms - prev:7.3f}")
        prev = ms


if __name__ == "__main__":
    main()
