#!/bin/bash
# InLoc evaluation assets: database cutouts (+ XYZcut depth .mat) and iPhone 7
# query images.  ~100 GB total.  Run from this directory: bash download.sh
#
# Also needed, from the InLoc project page (http://www.ok.sc.e.titech.ac.jp/INLOC/):
#   densePE_top100_shortlist_cvpr18.mat   (per-query retrieval shortlist)
#   scans/ + <floor>/transformations/     (for pose verification)
#   DUC_refposes_all.mat                  (ground-truth poses, for the curves)
set -e

wget -c http://www.ok.sc.e.titech.ac.jp/INLOC/materials/cutouts.tar.gz
wget -c http://www.ok.sc.e.titech.ac.jp/INLOC/materials/iphone7.tar.gz

mkdir -p pano query
tar -xzf cutouts.tar.gz -C pano
tar -xzf iphone7.tar.gz -C query
