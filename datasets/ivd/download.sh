#!/bin/bash
# Indoor venues dataset (IVD): 3,708 Google-Maps photos of 89 venues.  The
# pair lists (image_pairs/), directory tree (dirs.txt) and image URL list
# (urls.txt) are vendored — only the images themselves need fetching.
# Run from this directory: bash download.sh
set -e

bash make_dirs.sh

# urls.txt rows are "<relative path> <url>"; fetch 8-wide, tolerate misses
# (venue photos occasionally disappear from Google Maps)
<urls.txt xargs -n2 -P8 wget -nc -O || true
