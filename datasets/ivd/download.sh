#!/bin/bash
# Indoor venues dataset (IVD): 3,708 Google-Maps photos of 89 venues.  The
# pair lists (image_pairs/), directory tree (dirs.txt) and image URL list
# (urls.txt) are vendored — only the images themselves need fetching.
# Run from this directory: bash download.sh
set -e

bash make_dirs.sh

# a transiently-failed `wget -nc -O` leaves a 0-byte file that -nc then
# skips forever (ADVICE r3): clear any such husks so reruns retry them
find . -name '*.jpg' -size 0 -delete 2>/dev/null || true

# urls.txt rows are "<relative path> <url>"; fetch 8-wide, tolerate misses
# (venue photos occasionally disappear from Google Maps).  Fetch to a temp
# name and mv on success so a failed fetch cannot masquerade as done.
fetch_one() {
    local path="$1" url="$2"
    [ -s "$path" ] && return 0
    if wget -q -O "$path.part" "$url"; then
        mv "$path.part" "$path"
    else
        rm -f "$path.part"
        return 1
    fi
}
export -f fetch_one
<urls.txt xargs -n2 -P8 bash -c 'fetch_one "$@"' _ || true
