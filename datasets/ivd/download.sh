#!/bin/bash
# Indoor venues dataset (IVD): 1,854 Google-Maps photos of 89 venues, plus the
# NCNet pair lists.  Run from this directory: bash download.sh
set -e

BASE=https://raw.githubusercontent.com/ignacio-rocco/ncnet/master/datasets/ivd

# directory tree + image URL list (data files maintained upstream)
wget -c -O dirs.txt $BASE/dirs.txt
wget -c -O urls.txt $BASE/urls.txt

while read -r path _; do
  mkdir -p "$path"
done < dirs.txt

# urls.txt rows are "<relative path> <url>"; fetch 8-wide, tolerate misses
# (venue photos occasionally disappear from Google Maps)
<urls.txt xargs -n2 -P8 wget -nc -O || true

mkdir -p image_pairs
for f in train_pairs.csv val_pairs.csv; do
  wget -c -O image_pairs/$f $BASE/image_pairs/$f
done
