#!/bin/bash
# Create the 89-venue directory tree listed in dirs.txt (reference
# datasets/ivd/make_dirs.sh).
set -e
while read -r path _; do
  mkdir -p "$path"
done < dirs.txt
