#!/bin/bash
# PF-Pascal images (Proposal Flow, Ham et al.) + the NCNet pair lists.
# Run from this directory: bash download.sh
set -e

# images (same public source the reference uses)
wget -c https://www.di.ens.fr/willow/research/proposalflow/dataset/PF-dataset-PASCAL.zip
unzip -n PF-dataset-PASCAL.zip 'PF-dataset-PASCAL/JPEGImages/*'

# curated pair lists, fetched from the upstream NCNet repository
mkdir -p image_pairs
BASE=https://raw.githubusercontent.com/ignacio-rocco/ncnet/master/datasets/pf-pascal/image_pairs
for f in train_pairs.csv val_pairs.csv test_pairs.csv; do
  wget -c -O image_pairs/$f $BASE/$f
done
