#!/bin/bash
# PF-Pascal images (Proposal Flow, Ham et al.).  The curated pair lists are
# vendored in image_pairs/ — only the images need fetching.
# Run from this directory: bash download.sh
set -e

# images (same public source the reference uses)
wget -c https://www.di.ens.fr/willow/research/proposalflow/dataset/PF-dataset-PASCAL.zip
unzip -n PF-dataset-PASCAL.zip 'PF-dataset-PASCAL/JPEGImages/*'
