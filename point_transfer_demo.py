#!/usr/bin/env python
"""Point-transfer demo: the reference notebook as a headless script.

Loads a checkpoint (or a random tiny model), runs one PF-Pascal pair through
the model, transfers the annotated keypoints from target to source via
``corr_to_matches`` + ``bilinear_interp_point_tnf``, and writes a
side-by-side visualization — the de-facto smoke test of the whole inference
path (reference: point_transfer_demo.ipynb cells 3, 5, 7; SURVEY §3.5).

    python point_transfer_demo.py --eval_dataset_path datasets/pf-pascal/ \
        --checkpoint trained_models/... --out demo.png

Without a dataset on disk, --synthetic fabricates a shifted pair with known
ground truth so the demo runs hermetically.
"""

import argparse


def build_parser():
    p = argparse.ArgumentParser(description="NCNet point transfer demo")
    p.add_argument("--checkpoint", type=str, default="")
    p.add_argument("--eval_dataset_path", type=str, default="datasets/pf-pascal/")
    p.add_argument("--image_size", type=int, default=400)
    p.add_argument("--pair_idx", type=int, default=0)
    p.add_argument("--backbone", type=str, default="resnet101",
                   help="used only when no checkpoint is given")
    p.add_argument("--synthetic", action="store_true",
                   help="fabricate a synthetic shifted pair (no dataset needed)")
    p.add_argument("--out", type=str, default="point_transfer_demo.png")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import numpy as np
    import jax.numpy as jnp

    from ncnet_tpu.config import ModelConfig
    from ncnet_tpu.data import PFPascalDataset
    from ncnet_tpu.models import NCNet
    from ncnet_tpu.ops import (
        bilinear_interp_point_tnf,
        points_to_pixel_coords,
        points_to_unit_coords,
    )
    from ncnet_tpu.utils.plot import plot_image

    if args.synthetic:
        import tempfile

        from ncnet_tpu.data.synthetic import write_pf_pascal_like

        root = tempfile.mkdtemp()
        write_pf_pascal_like(root, n_pairs=1, image_hw=(args.image_size,) * 2,
                             shift=(args.image_size // 8,) * 2)
        args.eval_dataset_path = root

    net = NCNet(ModelConfig(backbone=args.backbone, checkpoint=args.checkpoint))
    dataset = PFPascalDataset(
        csv_file=f"{args.eval_dataset_path.rstrip('/')}/image_pairs/test_pairs.csv",
        dataset_path=args.eval_dataset_path,
        output_size=(args.image_size, args.image_size),
        pck_procedure="pf",
        # the warm matcher normalizes on device: the demo uploads the raw
        # resized uint8 pixels (4× fewer bytes through a tunneled device)
        normalize=False,
    )
    sample = dataset[args.pair_idx]

    from ncnet_tpu.ops.image import quantize_u8

    src_u8 = quantize_u8(sample["source_image"])[None]
    tgt_u8 = quantize_u8(sample["target_image"])[None]

    # the persistent warm single-pair path (models/ncnet.py
    # make_point_matcher): weights pre-staged, uint8 upload, device-side
    # normalization + match extraction, compact table download — the bs1
    # wall through a tunneled device drops from ~44× to ~a few× device time
    from ncnet_tpu.models import make_point_matcher

    matcher = make_point_matcher(net.config, net.params, do_softmax=True)
    matches = matcher(src_u8, tgt_u8)
    # plot_image expects ImageNet-normalized pixels — normalize on host for
    # display only (the model input already normalized on device)
    from ncnet_tpu.ops.image import normalize_imagenet

    src = normalize_imagenet(src_u8.astype(np.float32))
    tgt = normalize_imagenet(tgt_u8.astype(np.float32))

    tgt_pts = jnp.asarray(sample["target_points"])[None]   # (1, 2, 20), −1 pad
    n_valid = int(np.sum(np.asarray(tgt_pts)[0, 0] != -1))
    tgt_size = jnp.asarray(sample["target_im_size"])[None]
    src_size = jnp.asarray(sample["source_im_size"])[None]

    tgt_norm = points_to_unit_coords(tgt_pts, tgt_size)
    warped_norm = bilinear_interp_point_tnf(matches, tgt_norm)
    warped = np.asarray(points_to_pixel_coords(warped_norm, src_size))[0]
    tgt_px = np.asarray(tgt_pts)[0]
    src_px = np.asarray(sample["source_points"])

    # display coords: dataset points are in ORIGINAL pixel space; images shown
    # at the resized square — rescale for drawing
    def to_disp(pts, size):
        scale = np.array([[args.image_size / float(size[1])],
                          [args.image_size / float(size[0])]])
        return pts * scale

    h_s, w_s = float(src_size[0, 0]), float(src_size[0, 1])
    h_t, w_t = float(tgt_size[0, 0]), float(tgt_size[0, 1])
    warped_d = to_disp(warped[:, :n_valid], (h_s, w_s))
    srcgt_d = to_disp(src_px[:, :n_valid], (h_s, w_s))
    tgt_d = to_disp(tgt_px[:, :n_valid], (h_t, w_t))

    fig, (ax_s, ax_t) = plt.subplots(1, 2, figsize=(10, 5))
    plot_image(np.asarray(src), ax=ax_s)
    plot_image(np.asarray(tgt), ax=ax_t)
    colors = plt.cm.tab20(np.linspace(0, 1, max(n_valid, 1)))
    ax_t.scatter(tgt_d[0], tgt_d[1], c=colors[:n_valid], s=40,
                 edgecolors="white", label="target keypoints")
    ax_s.scatter(warped_d[0], warped_d[1], c=colors[:n_valid], s=40,
                 marker="o", edgecolors="white", label="transferred")
    ax_s.scatter(srcgt_d[0], srcgt_d[1], s=70, facecolors="none",
                 edgecolors=colors[:n_valid], marker="s", label="ground truth")
    ax_s.set_title("source: transferred (o) vs GT (□)")
    ax_t.set_title("target: annotated keypoints")
    err = np.linalg.norm(warped[:, :n_valid] - src_px[:, :n_valid], axis=0)
    fig.suptitle(f"mean transfer error: {float(err.mean()):.1f} px "
                 f"({n_valid} keypoints)")
    fig.savefig(args.out, dpi=120, bbox_inches="tight")
    print(f"wrote {args.out}  (mean error {float(err.mean()):.2f} px)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
