#!/usr/bin/env python
"""Entry shim: PF-Pascal PCK evaluation (see ncnet_tpu/cli/eval_pf_pascal.py)."""
import sys

from ncnet_tpu.cli.eval_pf_pascal import main

if __name__ == "__main__":
    sys.exit(main())
